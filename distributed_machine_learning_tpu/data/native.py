"""ctypes bindings for the native C++ data-layer kernels.

The hot host-side data-prep ops (windowing, shuffled batch gather,
standardization — the work the reference does in Python loops / delegates to
torch DataLoaders, `ray-tune-hpo-regression.py:403-411,452-457`) live in
``native/window_ops.cpp`` as a C-ABI shared library with OpenMP. This module
compiles it with the system ``g++`` on first use (cached by source hash under
``~/.cache/dml_tpu/``), binds it with ctypes, and exposes numpy-signature
wrappers. Every wrapper has a pure-numpy fallback, so the package works
identically (slower) where no C++ toolchain exists; ``native_available()``
reports which path is active.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional, Tuple

import numpy as np
from distributed_machine_learning_tpu.analysis.locks import named_lock

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native", "window_ops.cpp")
_CACHE_DIR = os.environ.get(
    "DML_TPU_NATIVE_CACHE", os.path.join(os.path.expanduser("~"), ".cache", "dml_tpu")
)

_lock = named_lock("data.native")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    """Compile window_ops.cpp -> .so (hash-cached) and dlopen it."""
    try:
        with open(_SRC, "rb") as f:
            src = f.read()
    except OSError:
        return None
    tag = hashlib.sha256(src).hexdigest()[:16]
    so_path = os.path.join(_CACHE_DIR, f"libdmlnative_{tag}.so")
    if not os.path.exists(so_path):
        os.makedirs(_CACHE_DIR, exist_ok=True)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_CACHE_DIR)
        os.close(fd)
        cmd = [
            "g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-fopenmp",
            _SRC, "-o", tmp,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so_path)
        except (subprocess.SubprocessError, OSError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None

    i64 = ctypes.c_int64
    u64 = ctypes.c_uint64
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")

    lib.dml_window.argtypes = [f32p, i64, i64, i64, i64, f32p]
    lib.dml_window.restype = i64
    lib.dml_gather.argtypes = [f32p, i64, i64, i64p, i64, f32p]
    lib.dml_gather.restype = i64
    lib.dml_shuffled_indices.argtypes = [i64, u64, i64p]
    lib.dml_shuffled_indices.restype = i64
    lib.dml_column_stats.argtypes = [f32p, i64, i64, f64p, f64p]
    lib.dml_column_stats.restype = i64
    lib.dml_standardize.argtypes = [f32p, i64, i64, f64p, f64p, ctypes.c_double]
    lib.dml_standardize.restype = i64
    lib.dml_rolling_stats.argtypes = [f32p, i64, i64p, i64, f32p]
    lib.dml_rolling_stats.restype = i64
    return lib


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if not _tried:
            if os.environ.get("DML_TPU_DISABLE_NATIVE"):
                _lib = None
            else:
                _lib = _build_and_load()
            _tried = True
    return _lib


def native_available() -> bool:
    return _get_lib() is not None


def window(array: np.ndarray, interval: int, stride: int) -> np.ndarray:
    """[T, F] float32 -> [n_windows, interval, F]; native parallel memcpy."""
    if array.ndim == 1:
        array = array[:, None]
    T, F = array.shape
    if T < interval:
        return np.empty((0, interval, F), dtype=np.float32)
    n_windows = (T - interval) // stride + 1
    lib = _get_lib()
    arr = np.ascontiguousarray(array, dtype=np.float32)
    if lib is None:
        w = np.lib.stride_tricks.sliding_window_view(arr, interval, axis=0)
        return np.ascontiguousarray(np.transpose(w[::stride], (0, 2, 1)))
    out = np.empty((n_windows, interval, F), dtype=np.float32)
    rc = lib.dml_window(arr, T, F, interval, stride, out)
    if rc != n_windows:  # pragma: no cover
        raise RuntimeError(f"dml_window failed: rc={rc}")
    return out


_SM64_MIX = np.uint64(0xD1B54A32D192ED03)
_SM64_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_SM64_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SM64_M2 = np.uint64(0x94D049BB133111EB)


def _splitmix64_draws(seed: int, count: int) -> np.ndarray:
    """The first ``count`` splitmix64 outputs for ``seed`` (vectorized;
    bit-identical to ``splitmix64`` in native/window_ops.cpp)."""
    state = np.uint64(seed & (2**64 - 1)) ^ _SM64_MIX
    with np.errstate(over="ignore"):
        z = state + np.arange(1, count + 1, dtype=np.uint64) * _SM64_GOLDEN
        z = (z ^ (z >> np.uint64(30))) * _SM64_M1
        z = (z ^ (z >> np.uint64(27))) * _SM64_M2
        return z ^ (z >> np.uint64(31))


def shuffled_indices(n: int, seed: int) -> np.ndarray:
    """Deterministic permutation of [0, n) (the epoch shuffle in
    Dataset.batches). The numpy fallback implements the same splitmix64
    Fisher-Yates as the native path, so a given seed produces the same batch
    order whether or not the C++ toolchain built — training runs stay
    reproducible across hosts with and without g++."""
    lib = _get_lib()
    out = np.empty(n, dtype=np.int64)
    if lib is None:
        out[:] = np.arange(n)
        draws = _splitmix64_draws(seed, max(n - 1, 0))
        for k, i in enumerate(range(n - 1, 0, -1)):
            j = int(draws[k] % np.uint64(i + 1))
            out[i], out[j] = out[j], out[i]
        return out
    lib.dml_shuffled_indices(n, np.uint64(seed & (2**64 - 1)), out)
    return out


def gather(x: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """x[idx] for row-major float32 x of any trailing shape.

    Negative indices are rejected on both paths (numpy's wrap-around would
    otherwise make behavior toolchain-dependent).
    """
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    if idx.size and (idx.min() < 0 or idx.max() >= len(x)):
        raise IndexError("gather index out of range")
    lib = _get_lib()
    if lib is None:
        return x[idx]
    x = np.ascontiguousarray(x, dtype=np.float32)
    row_elems = int(np.prod(x.shape[1:], dtype=np.int64)) if x.ndim > 1 else 1
    out = np.empty((len(idx),) + x.shape[1:], dtype=np.float32)
    lib.dml_gather(x.reshape(len(x), -1) if x.ndim > 1 else x[:, None],
                   len(x), max(row_elems, 1), idx, len(idx),
                   out.reshape(len(idx), -1) if out.ndim > 1 else out[:, None])
    return out


def rolling_stats(series: np.ndarray, windows, ddof: int = 0) -> np.ndarray:
    """Trailing rolling mean/std of a 1-D series over several windows.

    Returns [n, len(windows)*2], columns (mean_w0, std_w0, mean_w1, ...).
    Semantics match ``pandas.rolling(w, min_periods=1)``, including NaN
    handling: NaN entries are skipped per-window (sensor gaps), and a
    window with no finite entries yields NaN. ``ddof=0`` (default) is
    population std; ``ddof=1`` matches pandas' ``.rolling().std()``
    default (NaN wherever the finite count is <= ddof) — the reference's
    precomputed '*_std_*min' data columns may use either convention, so
    both are exposed. Both paths compute through the same double prefix
    sums, so results agree to float32 rounding with or without the C++
    toolchain.
    """
    x = np.ascontiguousarray(np.asarray(series).reshape(-1), dtype=np.float32)
    ws = np.ascontiguousarray(np.asarray(list(windows)), dtype=np.int64)
    n, k = len(x), len(ws)
    if ddof < 0:
        raise ValueError(f"ddof must be >= 0: {ddof}")
    if n == 0 or k == 0:
        return np.empty((n, k * 2), dtype=np.float32)
    if (ws <= 0).any():
        raise ValueError(f"window lengths must be positive: {ws}")
    lib = _get_lib()
    if lib is not None:
        out = np.empty((n, k * 2), dtype=np.float32)
        rc = lib.dml_rolling_stats(x, n, ws, k, out)
        if rc != n:  # pragma: no cover
            raise RuntimeError(f"dml_rolling_stats failed: rc={rc}")
        return _apply_ddof(out, x, ws, ddof)
    xd = x.astype(np.float64)
    ok = np.isfinite(xd)
    xz = np.where(ok, xd, 0.0)
    s1 = np.concatenate([[0.0], np.cumsum(xz)])
    s2 = np.concatenate([[0.0], np.cumsum(xz * xz)])
    sc = np.concatenate([[0.0], np.cumsum(ok.astype(np.float64))])
    idx = np.arange(n)
    out = np.empty((n, k * 2), dtype=np.float32)
    with np.errstate(invalid="ignore", divide="ignore"):
        for j, w in enumerate(ws):
            lo = np.maximum(idx - int(w) + 1, 0)
            cnt = sc[idx + 1] - sc[lo]
            mu = np.where(cnt > 0, (s1[idx + 1] - s1[lo]) / cnt, np.nan)
            var = np.maximum((s2[idx + 1] - s2[lo]) / cnt - mu * mu, 0.0)
            out[:, j * 2] = mu
            out[:, j * 2 + 1] = np.sqrt(var) * _ddof_factor(cnt, ddof)
    return out


def _ddof_factor(cnt: np.ndarray, ddof: int) -> np.ndarray:
    """Population-std -> ddof-std rescale per window: sqrt(cnt/(cnt-ddof)),
    NaN where cnt <= ddof (pandas convention). 1.0 at ddof=0."""
    if ddof == 0:
        return np.ones_like(cnt)
    return np.sqrt(
        np.where(cnt > ddof, cnt / np.maximum(cnt - ddof, 1e-300), np.nan)
    )


def _apply_ddof(out: np.ndarray, x: np.ndarray, ws: np.ndarray,
                ddof: int) -> np.ndarray:
    """Rescale the native kernel's population-std columns to ``ddof``
    freedom. The per-window finite counts come from one prefix sum over
    the finite mask — O(n*k) numpy, so the native kernel stays a single
    population-stats entry point."""
    if ddof == 0:
        return out
    n = len(x)
    sc = np.concatenate(
        [[0.0], np.cumsum(np.isfinite(x).astype(np.float64))]
    )
    idx = np.arange(n)
    with np.errstate(invalid="ignore", divide="ignore"):
        for j, w in enumerate(ws):
            lo = np.maximum(idx - int(w) + 1, 0)
            cnt = sc[idx + 1] - sc[lo]
            out[:, j * 2 + 1] = (
                out[:, j * 2 + 1].astype(np.float64) * _ddof_factor(cnt, ddof)
            ).astype(np.float32)
    return out


def standardize(
    x: np.ndarray, eps: float = 1e-8
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-column z-score of [N, F] float32; returns (standardized, mean, std)."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    n, m = x.shape
    lib = _get_lib()
    if lib is None:
        mean = x.mean(axis=0, dtype=np.float64)
        std = x.std(axis=0, dtype=np.float64)
        scaled = (x - mean) / np.where(std > eps, std, 1.0)
        return scaled.astype(np.float32), mean, std
    mean = np.empty(m, dtype=np.float64)
    std = np.empty(m, dtype=np.float64)
    lib.dml_column_stats(x, n, m, mean, std)
    out = x.copy()
    lib.dml_standardize(out, n, m, mean, std, eps)
    return out, mean, std
