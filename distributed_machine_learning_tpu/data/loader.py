"""Data pipeline: .npy DataFrame loading, windowing, splits, and batch iteration.

Capability parity with the reference's L0 data layer (SURVEY.md §1):

* ``load_dataframe_from_npy`` — pickled ``{"columns": ..., "data": ...}`` dict
  in a ``.npy`` file -> DataFrame (`ray-tune-hpo-regression.py:414-418`).
* ``split_into_intervals`` — strided sliding-window segmentation
  (`:403-411`), here a zero-copy ``sliding_window_view`` instead of the
  reference's python loop over intervals.
* ``make_regression_dataset`` / ``get_dataset`` — the `get_data_loaders`
  pipeline (`:423-459`): feature selection, column dedup, label extraction,
  windowing (interval=96, stride=96), deterministic 70/30 split.
* ``Dataset`` — an ndarray-backed batch source replacing torch
  ``TensorDataset``/``DataLoader``: shuffled batching with a dropped remainder
  produces the static shapes jit wants, and ``as_jax`` stages the whole set to
  device once (HBM-resident epochs; no per-batch host->device copies, unlike
  the reference's per-batch ``.to(device)`` at `:327`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from distributed_machine_learning_tpu.data import features as F
from distributed_machine_learning_tpu.utils.seeding import fold_seed, rng_from


def load_dataframe_from_npy(path: str):
    """Load a DataFrame stored as a pickled {columns, data} dict in .npy."""
    import pandas as pd

    payload = np.load(path, allow_pickle=True).item()
    return pd.DataFrame(payload["data"], columns=payload["columns"])


def split_into_intervals(
    array: np.ndarray, interval: int, stride: int
) -> np.ndarray:
    """[T, F] -> [num_intervals, interval, F] with the given stride.

    Native C++/OpenMP when available (data/native.py), stride-tricks numpy
    otherwise (the reference loops in python, `:403-411`).
    """
    if array.ndim == 1:
        array = array[:, None]
    T = array.shape[0]
    if T < interval:
        return np.empty((0, interval, array.shape[1]), dtype=array.dtype)
    if array.dtype == np.float32:
        from distributed_machine_learning_tpu.data import native

        return native.window(array, interval, stride)
    windows = np.lib.stride_tricks.sliding_window_view(array, interval, axis=0)
    # sliding_window_view gives [T-interval+1, F, interval]; stride + reorder.
    return np.ascontiguousarray(np.transpose(windows[::stride], (0, 2, 1)))


@dataclass
class Dataset:
    """A fully materialized (x, y) array pair with seeded batch iteration."""

    x: np.ndarray
    y: np.ndarray

    def __post_init__(self):
        if len(self.x) != len(self.y):
            raise ValueError(f"x/y length mismatch: {len(self.x)} vs {len(self.y)}")

    def __len__(self) -> int:
        return len(self.x)

    @property
    def num_features(self) -> int:
        return int(self.x.shape[-1])

    def batches(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed_parts: Sequence = (0,),
        drop_remainder: bool = True,
        with_mask: bool = False,
    ) -> Iterator[Tuple[np.ndarray, ...]]:
        """Yield (x, y) batches. Static batch shape by default (jit-friendly).

        A dataset smaller than ``batch_size`` yields ONE batch zero-padded
        to exactly ``batch_size`` (it used to emit a ragged batch, which
        silently broke the static-shape jit contract — every odd dataset
        size forced its own recompile).  ``with_mask=True`` yields
        ``(x, y, mask)`` triples (``mask`` is float32, 1.0 for real rows)
        so consumers can weight the padding out of their loss; it also
        pads the final ragged batch under ``drop_remainder=False`` (whose
        legacy ragged yield is kept when no mask is requested — padding
        without a mask would silently dilute a loss).
        """
        from distributed_machine_learning_tpu.data import native as _native

        n = len(self)
        if shuffle:
            # Native Fisher-Yates (C++/OpenMP) when the library is built,
            # numpy permutation otherwise; both deterministic in seed_parts.
            idx = _native.shuffled_indices(n, fold_seed(*seed_parts))
        else:
            idx = np.arange(n)
        end = (n // batch_size) * batch_size if drop_remainder else n
        if end == 0:
            end = n  # tiny dataset: one batch, PADDED to batch_size below
        if self.x.dtype == np.float32 and self.y.dtype == np.float32:
            take = _native.gather
        else:
            take = lambda a, sel: a[sel]  # noqa: E731
        for start in range(0, end, batch_size):
            sel = idx[start : start + batch_size]
            bx, by = take(self.x, sel), take(self.y, sel)
            short = batch_size - len(sel)
            # Tiny datasets always pad (the static-shape contract);
            # a drop_remainder=False ragged TAIL pads only when the mask
            # can carry the truth.
            if short > 0 and (start == 0 or with_mask):
                bx = np.concatenate(
                    [bx, np.zeros((short, *bx.shape[1:]), bx.dtype)]
                )
                by = np.concatenate(
                    [by, np.zeros((short, *by.shape[1:]), by.dtype)]
                )
            if with_mask:
                mask = np.ones(len(bx), np.float32)
                if short > 0:
                    mask[len(sel):] = 0.0
                yield bx, by, mask
            else:
                yield bx, by

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        n = len(self)
        return max(n // batch_size if drop_remainder else -(-n // batch_size), 1)

    def as_jax(self, device=None, enforce_budget: bool = False):
        """Stage the full arrays onto a device once (HBM-resident epochs).

        ``enforce_budget=True`` first checks the staged bytes against the
        device's accelerator-memory budget
        (``models/flagship.single_chip_hbm_bytes`` — the virtual
        ``DML_CPU_DEVICE_BUDGET_BYTES`` budget on CPU) and raises
        ``data.pipeline.ResidentOverBudgetError`` for a dataset that
        provably cannot stage — the out-of-core alternative is the
        streaming prefetch ring (``input_mode="streaming"``).
        """
        import jax

        if enforce_budget:
            from distributed_machine_learning_tpu.data.pipeline import (
                check_resident_budget,
            )

            check_resident_budget(
                int(self.x.nbytes) + int(self.y.nbytes), device,
                what="Dataset.as_jax",
            )
        if device is not None:
            return (
                jax.device_put(self.x, device),
                jax.device_put(self.y, device),
            )
        return jax.numpy.asarray(self.x), jax.numpy.asarray(self.y)


# ---------------------------------------------------------------------------
# Dataset-rebuild disk cache: windowed/standardized arrays shared across
# trial processes
# ---------------------------------------------------------------------------

CACHE_DIR_ENV_VAR = "DML_DATASET_CACHE_DIR"


def dataset_cache_dir(explicit: Optional[str] = None) -> Optional[str]:
    """Resolve the windowed-array cache directory: explicit argument, else
    ``$DML_DATASET_CACHE_DIR``, else disabled (None)."""
    raw = explicit or os.environ.get(CACHE_DIR_ENV_VAR)
    return os.path.expanduser(raw) if raw else None


def _window_cache_key(
    x: np.ndarray, y: np.ndarray, interval: int, stride: int,
    standardize: bool, nan_policy: str,
) -> str:
    """Content key for one windowed build: sha256 over the SOURCE bytes
    (post feature-selection, pre window) plus every parameter that shapes
    the product — two trials re-windowing the same source hit the same
    file; any content or parameter change misses honestly."""
    import hashlib

    h = hashlib.sha256()
    for arr in (np.ascontiguousarray(x), np.ascontiguousarray(y)):
        h.update(str((arr.shape, arr.dtype.str)).encode())
        h.update(arr.tobytes())
    h.update(
        f"interval={interval}/stride={stride}/standardize={standardize}"
        f"/nan={nan_policy}/v1".encode()
    )
    return h.hexdigest()[:32]


def _atomic_np_save(path: str, arr: np.ndarray) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.save(f, arr)
    os.replace(tmp, path)  # atomic: readers see whole files or nothing


def _windowed_via_store(cache_dir, key, build, counters):
    """Content-store variant of the window cache: the ``.npy`` payloads
    are published as blobs under ``<cache_dir>/.cas`` behind a
    ``dataset-win-<key>`` ref, so identical window products dedup against
    each other (and against anything else in a shared ``$DML_STORE_ROOT``)
    and unreferenced products are swept by the same reachability GC as
    checkpoints.  Readers still mmap the blob file directly — same page-
    cache sharing as the legacy ``win_*.npy`` layout.

    Returns ``(xw, yw)``, or None when the store path is unavailable
    (store disabled, or a non-mmappable remote scheme) — the caller then
    falls back to the legacy flat-file cache.
    """
    from distributed_machine_learning_tpu import store as store_lib

    if not store_lib.store_enabled():
        return None
    cas = store_lib.get_store(
        store_lib.store_root_for(os.path.join(cache_dir, "win"))
    )
    if "://" in cas.root and not cas.root.startswith("file://"):
        return None  # mmap consumers need a real local file
    ref_name = f"dataset-win-{key}"

    def _open(mapping):
        arrays = []
        for part in ("x", "y"):
            digest = mapping.get(part)
            path = cas.local_blob_path(digest) if digest else None
            if path is None:
                return None
            try:
                arrays.append(np.load(path, mmap_mode="r"))
            except (OSError, ValueError):
                return None
        return tuple(arrays)

    doc = cas.read_ref(ref_name)
    if doc:
        manifest = cas.read_manifest(doc.get("manifest")) or {}
        got = _open(manifest.get("files") or {})
        if got is not None:
            counters.add("dataset_cache_hits")
            counters.add(
                "dataset_cache_bytes",
                int(got[0].nbytes) + int(got[1].nbytes),
            )
            return got
    counters.add("dataset_cache_misses")
    xw, yw = build()
    try:
        import io

        with cas.pin() as pin:
            mapping = {}
            for part, arr in (("x", xw), ("y", yw)):
                buf = io.BytesIO()
                np.save(buf, np.ascontiguousarray(arr))
                digest = cas.put_blob(buf.getvalue())
                pin.add(digest)
                mapping[part] = digest
            manifest_digest = cas.put_manifest({
                "kind": "dataset-window",
                "key": key,
                "files": mapping,
                store_lib.MANIFEST_CHUNKS_KEY: sorted(set(mapping.values())),
            })
            pin.add(manifest_digest)
            cas.set_ref(ref_name, manifest_digest, meta={"key": key})
        got = _open(mapping)
        if got is not None:
            return got
    except (OSError, ValueError):
        pass  # cache write failure must never fail a build
    return xw, yw


def _windowed_arrays(
    x: np.ndarray,
    y: np.ndarray,
    interval: int,
    stride: int,
    standardize: bool,
    nan_policy: str,
    cache_dir: Optional[str],
) -> Tuple[np.ndarray, np.ndarray]:
    """Standardize + window (the expensive per-trial rebuild), optionally
    through the on-disk cache.

    With a cache directory, the windowed arrays are stored once per
    (source sha256, interval, stride, standardize, nan_policy) and
    reopened via ``np.load(mmap_mode="r")`` — process-pool children and
    cluster trials on one host then share the kernel PAGE CACHE for the
    windowed bytes instead of each re-running the windowing/standardize
    kernels (``dataset_cache_{hits,misses,bytes}`` counters, published in
    the ``host_input`` block)."""

    def build() -> Tuple[np.ndarray, np.ndarray]:
        xs = x
        if standardize:
            from distributed_machine_learning_tpu.data import native as _native

            xs, _, _ = _native.standardize(xs)
        xw = split_into_intervals(xs, interval, stride)
        yw = split_into_intervals(y, interval, stride)[:, -1, 0:1]
        return xw, yw

    if not cache_dir:
        return build()
    from distributed_machine_learning_tpu.data.pipeline import (
        get_host_input_counters,
    )

    counters = get_host_input_counters()
    key = _window_cache_key(x, y, interval, stride, standardize, nan_policy)
    via_store = _windowed_via_store(cache_dir, key, build, counters)
    if via_store is not None:
        return via_store
    os.makedirs(cache_dir, exist_ok=True)
    fx = os.path.join(cache_dir, f"win_{key}_x.npy")
    fy = os.path.join(cache_dir, f"win_{key}_y.npy")
    try:
        xw = np.load(fx, mmap_mode="r")
        yw = np.load(fy, mmap_mode="r")
        counters.add("dataset_cache_hits")
        counters.add("dataset_cache_bytes", int(xw.nbytes) + int(yw.nbytes))
        return xw, yw
    except (OSError, ValueError):
        pass  # miss (or a torn legacy file): rebuild and publish
    counters.add("dataset_cache_misses")
    xw, yw = build()
    try:
        _atomic_np_save(fx, xw)
        _atomic_np_save(fy, yw)
        # Serve THIS process from the mmap too: the windowed copy is
        # dropped and every consumer shares one page-cached file.
        return np.load(fx, mmap_mode="r"), np.load(fy, mmap_mode="r")
    except OSError:
        return xw, yw  # cache write failure must never fail a build


def train_val_split(
    x: np.ndarray,
    y: np.ndarray,
    val_fraction: float = 0.3,
    seed: int = 42,
    shuffle: bool = True,
) -> Tuple[Dataset, Dataset]:
    """Deterministic split, parity with `train_test_split(..., random_state=42)` (`:449`)."""
    n = len(x)
    idx = np.arange(n)
    if shuffle:
        rng_from("split", seed).shuffle(idx)
    n_val = int(round(n * val_fraction))
    val_idx, train_idx = idx[:n_val], idx[n_val:]
    return Dataset(x[train_idx], y[train_idx]), Dataset(x[val_idx], y[val_idx])


def make_regression_dataset(
    features_df,
    labels_df,
    feature_columns: Optional[Sequence[str]] = None,
    label_column: str = F.LABEL_COLUMN,
    interval: int = 96,
    stride: int = 96,
    val_fraction: float = 0.3,
    seed: int = 42,
    standardize: bool = False,
    nan_policy: str = "zero",
    cache_dir: Optional[str] = None,
) -> Tuple[Dataset, Dataset]:
    """The reference's `get_data_loaders` pipeline (`:423-459`), DataFrame -> Datasets.

    Selects feature columns (deduplicating, `:442-443`), extracts the label,
    windows both with (interval, stride), labels each window with its last-step
    glucose value, and splits 70/30. ``standardize=True`` z-scores the feature
    columns first (native one-pass Welford kernel) — a capability the reference
    lacked entirely (its raw sensor scales went straight into the model).

    ``nan_policy``: pandas-generated rolling-std columns carry NaN where the
    window had <= ddof samples (every real precomputed file's row 0), and one
    NaN feature turns the whole training loss NaN.  "zero" (default) replaces
    non-finite feature values with 0; "keep" passes them through.  Windows
    whose LABEL is non-finite are dropped under either policy — zeroing a
    target would silently train toward garbage.

    ``cache_dir`` (or ``$DML_DATASET_CACHE_DIR``) enables the windowed-
    array disk cache: the standardized/windowed product is stored once per
    (source sha256, interval, stride, standardize, nan_policy) and
    reopened via ``np.load(mmap_mode="r")``, so process-pool and cluster
    trials rebuilding the same dataset share page cache instead of
    re-windowing per trial (counters: ``dataset_cache_{hits,misses,bytes}``
    in the ``host_input`` block).
    """
    if nan_policy not in ("zero", "keep"):
        raise ValueError(f"unknown nan_policy {nan_policy!r}")
    if feature_columns is not None:
        cols = [c for c in dict.fromkeys(feature_columns) if c in features_df.columns]
        features_df = features_df[cols]
    features_df = features_df.loc[:, ~features_df.columns.duplicated()]

    x = features_df.to_numpy(dtype=np.float32)
    y = labels_df[label_column].to_numpy(dtype=np.float32)
    if nan_policy == "zero":
        x = np.nan_to_num(x, nan=0.0, posinf=0.0, neginf=0.0)
    xw, yw = _windowed_arrays(
        x, y, interval, stride, standardize, nan_policy,
        dataset_cache_dir(cache_dir),
    )
    finite = np.isfinite(yw[:, 0])
    if not finite.all():
        xw, yw = xw[finite], yw[finite]
    # xw/yw may be mmap-backed (cache hit): the split's fancy indexing
    # materializes real in-memory splits from the page-cached file, so
    # the Datasets themselves never hold mmap views.
    return train_val_split(xw, yw, val_fraction=val_fraction, seed=seed)


def get_dataset(
    patient_id: str,
    data_dir: str,
    feature_columns: Optional[Sequence[str]] = None,
    **kwargs,
) -> Tuple[Dataset, Dataset]:
    """Load `{data_dir}/{id}_features.npy` + `{id}_labels.npy` and build datasets.

    Path scheme generalizes the reference's hard-coded home-dir paths
    (`:434-435`) into a configurable ``data_dir``.
    """
    fdf = load_dataframe_from_npy(os.path.join(data_dir, f"{patient_id}_features.npy"))
    ldf = load_dataframe_from_npy(os.path.join(data_dir, f"{patient_id}_labels.npy"))
    if feature_columns is None:
        # Schema auto-detection (VERDICT r3 next #3): a file using the
        # reference's literal column names (`/root/reference/config.py:2-78`,
        # selected at `ray-tune-hpo-regression.py:442`) selects the
        # reference's 81-column feature list; canonical frames get ours.
        if F.is_reference_format(fdf.columns):
            feature_columns = F.reference_features
            # Fail loudly on a partial/mixed-schema file: the selection
            # filter below silently drops absent columns, and training on
            # a drastically reduced feature set must not look like success.
            missing = [c for c in feature_columns if c not in fdf.columns]
            if missing:
                raise KeyError(
                    f"reference-format file for {patient_id!r} is missing "
                    f"{len(missing)}/81 expected columns (first: "
                    f"{missing[:4]}); pass feature_columns= explicitly to "
                    f"train on a subset"
                )
        else:
            feature_columns = F.features
    return make_regression_dataset(fdf, ldf, feature_columns=feature_columns, **kwargs)
