"""Data pipeline: .npy DataFrame loading, windowing, splits, and batch iteration.

Capability parity with the reference's L0 data layer (SURVEY.md §1):

* ``load_dataframe_from_npy`` — pickled ``{"columns": ..., "data": ...}`` dict
  in a ``.npy`` file -> DataFrame (`ray-tune-hpo-regression.py:414-418`).
* ``split_into_intervals`` — strided sliding-window segmentation
  (`:403-411`), here a zero-copy ``sliding_window_view`` instead of the
  reference's python loop over intervals.
* ``make_regression_dataset`` / ``get_dataset`` — the `get_data_loaders`
  pipeline (`:423-459`): feature selection, column dedup, label extraction,
  windowing (interval=96, stride=96), deterministic 70/30 split.
* ``Dataset`` — an ndarray-backed batch source replacing torch
  ``TensorDataset``/``DataLoader``: shuffled batching with a dropped remainder
  produces the static shapes jit wants, and ``as_jax`` stages the whole set to
  device once (HBM-resident epochs; no per-batch host->device copies, unlike
  the reference's per-batch ``.to(device)`` at `:327`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from distributed_machine_learning_tpu.data import features as F
from distributed_machine_learning_tpu.utils.seeding import fold_seed, rng_from


def load_dataframe_from_npy(path: str):
    """Load a DataFrame stored as a pickled {columns, data} dict in .npy."""
    import pandas as pd

    payload = np.load(path, allow_pickle=True).item()
    return pd.DataFrame(payload["data"], columns=payload["columns"])


def split_into_intervals(
    array: np.ndarray, interval: int, stride: int
) -> np.ndarray:
    """[T, F] -> [num_intervals, interval, F] with the given stride.

    Native C++/OpenMP when available (data/native.py), stride-tricks numpy
    otherwise (the reference loops in python, `:403-411`).
    """
    if array.ndim == 1:
        array = array[:, None]
    T = array.shape[0]
    if T < interval:
        return np.empty((0, interval, array.shape[1]), dtype=array.dtype)
    if array.dtype == np.float32:
        from distributed_machine_learning_tpu.data import native

        return native.window(array, interval, stride)
    windows = np.lib.stride_tricks.sliding_window_view(array, interval, axis=0)
    # sliding_window_view gives [T-interval+1, F, interval]; stride + reorder.
    return np.ascontiguousarray(np.transpose(windows[::stride], (0, 2, 1)))


@dataclass
class Dataset:
    """A fully materialized (x, y) array pair with seeded batch iteration."""

    x: np.ndarray
    y: np.ndarray

    def __post_init__(self):
        if len(self.x) != len(self.y):
            raise ValueError(f"x/y length mismatch: {len(self.x)} vs {len(self.y)}")

    def __len__(self) -> int:
        return len(self.x)

    @property
    def num_features(self) -> int:
        return int(self.x.shape[-1])

    def batches(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed_parts: Sequence = (0,),
        drop_remainder: bool = True,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yield (x, y) batches. Static batch shape by default (jit-friendly)."""
        from distributed_machine_learning_tpu.data import native as _native

        n = len(self)
        if shuffle:
            # Native Fisher-Yates (C++/OpenMP) when the library is built,
            # numpy permutation otherwise; both deterministic in seed_parts.
            idx = _native.shuffled_indices(n, fold_seed(*seed_parts))
        else:
            idx = np.arange(n)
        end = (n // batch_size) * batch_size if drop_remainder else n
        if end == 0:
            end = n  # tiny dataset: emit one ragged batch rather than nothing
        if self.x.dtype == np.float32 and self.y.dtype == np.float32:
            take = _native.gather
        else:
            take = lambda a, sel: a[sel]  # noqa: E731
        for start in range(0, end, batch_size):
            sel = idx[start : start + batch_size]
            yield take(self.x, sel), take(self.y, sel)

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        n = len(self)
        return max(n // batch_size if drop_remainder else -(-n // batch_size), 1)

    def as_jax(self, device=None):
        """Stage the full arrays onto a device once (HBM-resident epochs)."""
        import jax

        if device is not None:
            return (
                jax.device_put(self.x, device),
                jax.device_put(self.y, device),
            )
        return jax.numpy.asarray(self.x), jax.numpy.asarray(self.y)


def train_val_split(
    x: np.ndarray,
    y: np.ndarray,
    val_fraction: float = 0.3,
    seed: int = 42,
    shuffle: bool = True,
) -> Tuple[Dataset, Dataset]:
    """Deterministic split, parity with `train_test_split(..., random_state=42)` (`:449`)."""
    n = len(x)
    idx = np.arange(n)
    if shuffle:
        rng_from("split", seed).shuffle(idx)
    n_val = int(round(n * val_fraction))
    val_idx, train_idx = idx[:n_val], idx[n_val:]
    return Dataset(x[train_idx], y[train_idx]), Dataset(x[val_idx], y[val_idx])


def make_regression_dataset(
    features_df,
    labels_df,
    feature_columns: Optional[Sequence[str]] = None,
    label_column: str = F.LABEL_COLUMN,
    interval: int = 96,
    stride: int = 96,
    val_fraction: float = 0.3,
    seed: int = 42,
    standardize: bool = False,
    nan_policy: str = "zero",
) -> Tuple[Dataset, Dataset]:
    """The reference's `get_data_loaders` pipeline (`:423-459`), DataFrame -> Datasets.

    Selects feature columns (deduplicating, `:442-443`), extracts the label,
    windows both with (interval, stride), labels each window with its last-step
    glucose value, and splits 70/30. ``standardize=True`` z-scores the feature
    columns first (native one-pass Welford kernel) — a capability the reference
    lacked entirely (its raw sensor scales went straight into the model).

    ``nan_policy``: pandas-generated rolling-std columns carry NaN where the
    window had <= ddof samples (every real precomputed file's row 0), and one
    NaN feature turns the whole training loss NaN.  "zero" (default) replaces
    non-finite feature values with 0; "keep" passes them through.  Windows
    whose LABEL is non-finite are dropped under either policy — zeroing a
    target would silently train toward garbage.
    """
    if nan_policy not in ("zero", "keep"):
        raise ValueError(f"unknown nan_policy {nan_policy!r}")
    if feature_columns is not None:
        cols = [c for c in dict.fromkeys(feature_columns) if c in features_df.columns]
        features_df = features_df[cols]
    features_df = features_df.loc[:, ~features_df.columns.duplicated()]

    x = features_df.to_numpy(dtype=np.float32)
    y = labels_df[label_column].to_numpy(dtype=np.float32)
    if nan_policy == "zero":
        x = np.nan_to_num(x, nan=0.0, posinf=0.0, neginf=0.0)
    if standardize:
        from distributed_machine_learning_tpu.data import native as _native

        x, _, _ = _native.standardize(x)

    xw = split_into_intervals(x, interval, stride)
    yw = split_into_intervals(y, interval, stride)[:, -1, 0:1]  # last-step label
    finite = np.isfinite(yw[:, 0])
    if not finite.all():
        xw, yw = xw[finite], yw[finite]
    return train_val_split(xw, yw, val_fraction=val_fraction, seed=seed)


def get_dataset(
    patient_id: str,
    data_dir: str,
    feature_columns: Optional[Sequence[str]] = None,
    **kwargs,
) -> Tuple[Dataset, Dataset]:
    """Load `{data_dir}/{id}_features.npy` + `{id}_labels.npy` and build datasets.

    Path scheme generalizes the reference's hard-coded home-dir paths
    (`:434-435`) into a configurable ``data_dir``.
    """
    fdf = load_dataframe_from_npy(os.path.join(data_dir, f"{patient_id}_features.npy"))
    ldf = load_dataframe_from_npy(os.path.join(data_dir, f"{patient_id}_labels.npy"))
    if feature_columns is None:
        # Schema auto-detection (VERDICT r3 next #3): a file using the
        # reference's literal column names (`/root/reference/config.py:2-78`,
        # selected at `ray-tune-hpo-regression.py:442`) selects the
        # reference's 81-column feature list; canonical frames get ours.
        if F.is_reference_format(fdf.columns):
            feature_columns = F.reference_features
            # Fail loudly on a partial/mixed-schema file: the selection
            # filter below silently drops absent columns, and training on
            # a drastically reduced feature set must not look like success.
            missing = [c for c in feature_columns if c not in fdf.columns]
            if missing:
                raise KeyError(
                    f"reference-format file for {patient_id!r} is missing "
                    f"{len(missing)}/81 expected columns (first: "
                    f"{missing[:4]}); pass feature_columns= explicitly to "
                    f"train on a subset"
                )
        else:
            feature_columns = F.features
    return make_regression_dataset(fdf, ldf, feature_columns=feature_columns, **kwargs)
