from distributed_machine_learning_tpu.data import features
from distributed_machine_learning_tpu.data.loader import (
    Dataset,
    get_dataset,
    load_dataframe_from_npy,
    make_regression_dataset,
    split_into_intervals,
    train_val_split,
)
from distributed_machine_learning_tpu.data.synthetic import (
    california_housing_data,
    dummy_regression_data,
    glucose_like_data,
)

__all__ = [
    "features",
    "Dataset",
    "get_dataset",
    "load_dataframe_from_npy",
    "make_regression_dataset",
    "split_into_intervals",
    "train_val_split",
    "california_housing_data",
    "dummy_regression_data",
    "glucose_like_data",
]
