"""Wearable-sensor feature-name configuration.

Capability parity with the reference's `config.py:2-78` (heart-rate / sleep /
intensity / steps feature lists at rolling windows plus temporal sin/cos
encodings) and the assembly at `ray-tune-hpo-regression.py:13-19`.  The names
are generated from the window grid rather than hand-enumerated, which yields the
same shape of feature surface without copying the reference's literal tables.
"""

from __future__ import annotations

from typing import List

ROLLING_WINDOWS_MIN = (15, 30, 60, 120, 240, 480, 720, 1440)


def _rolling(base: str, stats=("mean", "std")) -> List[str]:
    return [f"{base}_{stat}_{w}min" for w in ROLLING_WINDOWS_MIN for stat in stats]


def sensor_features(base: str) -> List[str]:
    """Raw reading + rolling mean/std at each window for one sensor channel."""
    return [base] + _rolling(base)


heart_rate_features_1: List[str] = [sensor_features("heart_rate")[0]]
heart_rate_features_2: List[str] = _rolling("heart_rate")
sleep_features_1: List[str] = [sensor_features("sleep")[0]]
sleep_features_2: List[str] = _rolling("sleep")
intensity_features_1: List[str] = [sensor_features("intensity")[0]]
intensity_features_2: List[str] = _rolling("intensity")
steps_features_1: List[str] = [sensor_features("steps")[0]]
steps_features_2: List[str] = _rolling("steps")

# sin/cos encodings of time-of-day / day-of-week / day-of-month / month.
temporal_features: List[str] = [
    f"{unit}_{fn}"
    for unit in ("minute_of_day", "day_of_week", "day_of_month", "month")
    for fn in ("sin", "cos")
]

# Assembly parity with `ray-tune-hpo-regression.py:13-19`:
# features_1 = raw sensor channels + temporal; features = everything.
features_1: List[str] = (
    heart_rate_features_1
    + sleep_features_1
    + intensity_features_1
    + steps_features_1
    + temporal_features
)

features: List[str] = (
    heart_rate_features_1 + heart_rate_features_2
    + sleep_features_1 + sleep_features_2
    + intensity_features_1 + intensity_features_2
    + steps_features_1 + steps_features_2
    + temporal_features
)

LABEL_COLUMN = "Historic Glucose mg/dL"
