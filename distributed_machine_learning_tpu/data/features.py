"""Wearable-sensor feature-name configuration.

Capability parity with the reference's `config.py:2-78` (heart-rate / sleep /
intensity / steps feature lists at rolling windows plus temporal sin/cos
encodings) and the assembly at `ray-tune-hpo-regression.py:13-19`.  The names
are generated from the window grid rather than hand-enumerated, which yields the
same shape of feature surface without copying the reference's literal tables.
"""

from __future__ import annotations

from typing import List

ROLLING_WINDOWS_MIN = (15, 30, 60, 120, 240, 480, 720, 1440)


def _rolling(base: str, stats=("mean", "std")) -> List[str]:
    return [f"{base}_{stat}_{w}min" for w in ROLLING_WINDOWS_MIN for stat in stats]


def sensor_features(base: str) -> List[str]:
    """Raw reading + rolling mean/std at each window for one sensor channel."""
    return [base] + _rolling(base)


heart_rate_features_1: List[str] = [sensor_features("heart_rate")[0]]
heart_rate_features_2: List[str] = _rolling("heart_rate")
sleep_features_1: List[str] = [sensor_features("sleep")[0]]
sleep_features_2: List[str] = _rolling("sleep")
intensity_features_1: List[str] = [sensor_features("intensity")[0]]
intensity_features_2: List[str] = _rolling("intensity")
steps_features_1: List[str] = [sensor_features("steps")[0]]
steps_features_2: List[str] = _rolling("steps")

# sin/cos encodings of time-of-day / day-of-week / day-of-month / month.
temporal_features: List[str] = [
    f"{unit}_{fn}"
    for unit in ("minute_of_day", "day_of_week", "day_of_month", "month")
    for fn in ("sin", "cos")
]

# Assembly parity with `ray-tune-hpo-regression.py:13-19`:
# features_1 = raw sensor channels + temporal; features = everything.
features_1: List[str] = (
    heart_rate_features_1
    + sleep_features_1
    + intensity_features_1
    + steps_features_1
    + temporal_features
)

features: List[str] = (
    heart_rate_features_1 + heart_rate_features_2
    + sleep_features_1 + sleep_features_2
    + intensity_features_1 + intensity_features_2
    + steps_features_1 + steps_features_2
    + temporal_features
)

LABEL_COLUMN = "Historic Glucose mg/dL"

SENSOR_CHANNELS = ("heart_rate", "sleep", "intensity", "steps")

# ---------------------------------------------------------------------------
# Reference data-file schema (interop).
#
# The reference's data FILES carry precomputed feature columns under ITS
# naming scheme (`/root/reference/config.py:2-78`), which differs from the
# canonical names above in three ways: CamelCase bases, a 9-entry window grid
# (15/30/60/90/180/240/360/720/1440 min vs our 15/30/60/120/240/480/720/1440),
# and an inconsistent window suffix — heart-rate columns are
# ``HeartRate_15_Mean`` (no "min", `config.py:6-16`) while every other sensor
# is ``Sleep_15min_Mean`` style (`config.py:26-36,44-54,62-68`).  The lists
# below are GENERATED from those observed rules so a reference-format ``.npy``
# flows through ``get_dataset`` unchanged (VERDICT r3 next #3); the schema is
# selected automatically by ``is_reference_format``.

REFERENCE_WINDOWS_MIN = (15, 30, 60, 90, 180, 240, 360, 720, 1440)

# canonical channel -> (reference raw column, window-suffix style)
_REFERENCE_CHANNELS = {
    "heart_rate": ("HeartRate", ""),   # HeartRate_15_Mean — no "min" suffix
    "sleep": ("Sleep", "min"),         # Sleep_15min_Mean
    "intensity": ("Intensity", "min"),
    "steps": ("Steps", "min"),
}

# reference temporal name -> canonical name. Is_Weekend has no canonical
# sin/cos analogue (it is a binary flag, `config.py:72-78`).
_REFERENCE_TEMPORAL = {
    "MinuteOfDay_Sin": "minute_of_day_sin",
    "MinuteOfDay_Cos": "minute_of_day_cos",
    "DayOfWeek_Sin": "day_of_week_sin",
    "DayOfWeek_Cos": "day_of_week_cos",
    "Is_Weekend": "is_weekend",
}


def reference_rolling_features(channel: str) -> List[str]:
    """The reference's rolling mean/std column names for one sensor channel
    (its ``*_features_2`` lists), generated from the observed naming rules."""
    raw, suffix = _REFERENCE_CHANNELS[channel]
    return [
        f"{raw}_{w}{suffix}_{stat}"
        for w in REFERENCE_WINDOWS_MIN
        for stat in ("Mean", "Std")
    ]


reference_temporal_features: List[str] = list(_REFERENCE_TEMPORAL)

# Assembly exactly as `ray-tune-hpo-regression.py:18-19` orders it:
# features_1 = raw channels + temporal; features = features_1 + the four
# rolling blocks (NOT interleaved per channel) — column ORDER matters for
# interop, a permuted matrix breaks per-feature comparisons and any
# projection-weight exchange with a reference-trained model.
reference_features_1: List[str] = [
    _REFERENCE_CHANNELS[ch][0] for ch in SENSOR_CHANNELS
] + reference_temporal_features

reference_features: List[str] = reference_features_1 + [
    col
    for ch in SENSOR_CHANNELS
    for col in reference_rolling_features(ch)
]


def _reference_aliases() -> dict:
    """reference column name -> canonical column name (all 81)."""
    out = {}
    for ch, (raw, suffix) in _REFERENCE_CHANNELS.items():
        out[raw] = ch
        for w in REFERENCE_WINDOWS_MIN:
            for stat in ("Mean", "Std"):
                out[f"{raw}_{w}{suffix}_{stat}"] = f"{ch}_{stat.lower()}_{w}min"
    out.update(_REFERENCE_TEMPORAL)
    return out


REFERENCE_ALIASES: dict = _reference_aliases()


def is_reference_format(columns) -> bool:
    """Whether a column collection uses the reference's naming scheme —
    keyed on the raw CamelCase sensor columns, which exist in every
    reference data file and in no canonical frame."""
    cols = set(columns)
    return any(_REFERENCE_CHANNELS[ch][0] in cols for ch in SENSOR_CHANNELS)


def normalize_reference_frame(df):
    """Rename a reference-format DataFrame's columns to canonical names
    (unknown columns pass through untouched).  Selection via
    ``reference_features`` works WITHOUT this — it exists for users who
    want one naming scheme downstream (e.g. mixing file-loaded and
    ``compute_rolling_features``-derived frames)."""
    return df.rename(columns=REFERENCE_ALIASES)


def compute_rolling_features(df, channels=SENSOR_CHANNELS,
                             minutes_per_step: int = 1, ddof: int = 1,
                             windows=ROLLING_WINDOWS_MIN):
    """Add the rolling mean/std feature columns to a raw sensor DataFrame.

    The reference's data FILES carry these columns precomputed (its
    `config.py:2-78` only names them); this computes them from the raw
    streams — trailing windows of ``windows`` minutes (pandas
    ``rolling(min_periods=1)`` semantics) via the native prefix-sum kernel
    (`native/window_ops.cpp: dml_rolling_stats`).  ``ddof=1`` (default)
    matches pandas' ``.rolling().std()`` convention — what any real
    precomputed file was generated with (VERDICT r3 weak #6); pass
    ``ddof=0`` for population std.  ``minutes_per_step`` converts the
    window grid to row counts for data sampled at other cadences; pass
    ``windows=REFERENCE_WINDOWS_MIN`` to compute the reference's 9-window
    grid.  Returns a new DataFrame; input is unchanged.
    """
    import pandas as pd

    from distributed_machine_learning_tpu.data import native as _native

    if minutes_per_step <= 0:
        raise ValueError(f"minutes_per_step must be positive: {minutes_per_step}")
    bad = [w for w in windows if w % minutes_per_step != 0]
    if bad:
        # Refuse rather than silently mislabel: a '15min' column computed
        # over a different time span would feed the model wrong features.
        raise ValueError(
            f"sampling cadence {minutes_per_step}min does not divide "
            f"window(s) {bad} — the '{{w}}min' column names would lie"
        )
    steps = [w // minutes_per_step for w in windows]
    new_cols = {}
    for base in channels:
        if base not in df.columns:
            raise KeyError(f"raw channel {base!r} not in DataFrame columns")
        stats = _native.rolling_stats(
            df[base].to_numpy(dtype=float), steps, ddof=ddof
        )
        for j, w in enumerate(windows):
            new_cols[f"{base}_mean_{w}min"] = stats[:, j * 2]
            new_cols[f"{base}_std_{w}min"] = stats[:, j * 2 + 1]
    # One concat, not 64 inserts: avoids pandas block fragmentation.
    return pd.concat(
        [df.copy(), pd.DataFrame(new_cols, index=df.index)], axis=1
    )


def compute_temporal_features(df, timestamp_column: str = None):
    """Add the sin/cos temporal encoding columns from timestamps.

    Uses ``timestamp_column`` if given, else the DataFrame's DatetimeIndex.
    Encodings: minute-of-day / 1440, day-of-week / 7, day-of-month / 31,
    month / 12, each as (sin, cos) of the phase — the cyclic form the
    reference's `temporal_features` names (`config.py`).
    """
    import numpy as np
    import pandas as pd

    # DatetimeIndex either way: a converted Series would need the .dt
    # accessor for .hour/.dayofweek, a DatetimeIndex exposes them directly.
    ts = pd.DatetimeIndex(
        pd.to_datetime(df[timestamp_column])
        if timestamp_column
        else pd.to_datetime(df.index)
    )
    phases = {
        "minute_of_day": (ts.hour * 60 + ts.minute) / 1440.0,
        "day_of_week": ts.dayofweek / 7.0,
        "day_of_month": (ts.day - 1) / 31.0,
        "month": (ts.month - 1) / 12.0,
    }
    out = df.copy()
    for unit, phase in phases.items():
        angle = 2.0 * np.pi * np.asarray(phase, dtype=np.float64)
        out[f"{unit}_sin"] = np.sin(angle).astype(np.float32)
        out[f"{unit}_cos"] = np.cos(angle).astype(np.float32)
    return out


def build_feature_frame(raw_df, channels=SENSOR_CHANNELS,
                        minutes_per_step: int = 1,
                        timestamp_column: str = None,
                        schema: str = "canonical"):
    """Raw sensor streams -> the full feature column surface.

    One call takes a DataFrame of raw channels (+ timestamps) to the
    feature frame the reference's pipeline selects
    (`ray-tune-hpo-regression.py:18-19,442`), ready for
    ``make_regression_dataset``.

    ``schema="canonical"`` (default): the 76-column `features` surface
    (4 channels x (raw + 8 windows x mean/std) + 8 temporal encodings).
    ``schema="reference"``: the reference data files' exact 81-column
    surface — its 9-window grid, its CamelCase names (incl. the
    ``HeartRate_15_Mean`` vs ``Sleep_15min_Mean`` suffix inconsistency)
    and its binary ``Is_Weekend`` flag (`config.py:2-78`) — so generated
    files are byte-compatible with reference consumers and round-trip
    through ``get_dataset``'s reference-format path.
    """
    if schema == "canonical":
        out = compute_rolling_features(raw_df, channels, minutes_per_step)
        out = compute_temporal_features(out, timestamp_column)
        wanted = features
    elif schema == "reference":
        import pandas as pd

        out = compute_rolling_features(
            raw_df, channels, minutes_per_step,
            windows=REFERENCE_WINDOWS_MIN,
        )
        out = compute_temporal_features(out, timestamp_column)
        ts = pd.DatetimeIndex(
            pd.to_datetime(out[timestamp_column])
            if timestamp_column
            else pd.to_datetime(out.index)
        )
        out["is_weekend"] = (ts.dayofweek >= 5).astype("float32")
        # canonical -> reference names (alias map inverted; 1:1 by design).
        out = out.rename(
            columns={canon: ref for ref, canon in REFERENCE_ALIASES.items()}
        )
        wanted = reference_features
    else:
        raise ValueError(f"unknown schema {schema!r}")
    missing = [c for c in wanted if c not in out.columns]
    if missing:
        raise KeyError(f"feature columns missing after assembly: {missing}")
    return out[wanted]
