"""Wearable-sensor feature-name configuration.

Capability parity with the reference's `config.py:2-78` (heart-rate / sleep /
intensity / steps feature lists at rolling windows plus temporal sin/cos
encodings) and the assembly at `ray-tune-hpo-regression.py:13-19`.  The names
are generated from the window grid rather than hand-enumerated, which yields the
same shape of feature surface without copying the reference's literal tables.
"""

from __future__ import annotations

from typing import List

ROLLING_WINDOWS_MIN = (15, 30, 60, 120, 240, 480, 720, 1440)


def _rolling(base: str, stats=("mean", "std")) -> List[str]:
    return [f"{base}_{stat}_{w}min" for w in ROLLING_WINDOWS_MIN for stat in stats]


def sensor_features(base: str) -> List[str]:
    """Raw reading + rolling mean/std at each window for one sensor channel."""
    return [base] + _rolling(base)


heart_rate_features_1: List[str] = [sensor_features("heart_rate")[0]]
heart_rate_features_2: List[str] = _rolling("heart_rate")
sleep_features_1: List[str] = [sensor_features("sleep")[0]]
sleep_features_2: List[str] = _rolling("sleep")
intensity_features_1: List[str] = [sensor_features("intensity")[0]]
intensity_features_2: List[str] = _rolling("intensity")
steps_features_1: List[str] = [sensor_features("steps")[0]]
steps_features_2: List[str] = _rolling("steps")

# sin/cos encodings of time-of-day / day-of-week / day-of-month / month.
temporal_features: List[str] = [
    f"{unit}_{fn}"
    for unit in ("minute_of_day", "day_of_week", "day_of_month", "month")
    for fn in ("sin", "cos")
]

# Assembly parity with `ray-tune-hpo-regression.py:13-19`:
# features_1 = raw sensor channels + temporal; features = everything.
features_1: List[str] = (
    heart_rate_features_1
    + sleep_features_1
    + intensity_features_1
    + steps_features_1
    + temporal_features
)

features: List[str] = (
    heart_rate_features_1 + heart_rate_features_2
    + sleep_features_1 + sleep_features_2
    + intensity_features_1 + intensity_features_2
    + steps_features_1 + steps_features_2
    + temporal_features
)

LABEL_COLUMN = "Historic Glucose mg/dL"

SENSOR_CHANNELS = ("heart_rate", "sleep", "intensity", "steps")


def compute_rolling_features(df, channels=SENSOR_CHANNELS,
                             minutes_per_step: int = 1, ddof: int = 0):
    """Add the rolling mean/std feature columns to a raw sensor DataFrame.

    The reference's data FILES carry these columns precomputed (its
    `config.py:2-78` only names them); this computes them from the raw
    streams — trailing windows of ``ROLLING_WINDOWS_MIN`` minutes
    (pandas ``rolling(min_periods=1)`` semantics) via the native
    prefix-sum kernel (`native/window_ops.cpp: dml_rolling_stats`).
    ``ddof=0`` (default) is population std; pass ``ddof=1`` to match
    pandas' ``.rolling().std()`` default if the precomputed data files
    were generated that way. ``minutes_per_step`` converts the window
    grid to row counts for data sampled at other cadences. Returns a new
    DataFrame; input is unchanged.
    """
    import pandas as pd

    from distributed_machine_learning_tpu.data import native as _native

    if minutes_per_step <= 0:
        raise ValueError(f"minutes_per_step must be positive: {minutes_per_step}")
    bad = [w for w in ROLLING_WINDOWS_MIN if w % minutes_per_step != 0]
    if bad:
        # Refuse rather than silently mislabel: a '15min' column computed
        # over a different time span would feed the model wrong features.
        raise ValueError(
            f"sampling cadence {minutes_per_step}min does not divide "
            f"window(s) {bad} — the '{{w}}min' column names would lie"
        )
    steps = [w // minutes_per_step for w in ROLLING_WINDOWS_MIN]
    new_cols = {}
    for base in channels:
        if base not in df.columns:
            raise KeyError(f"raw channel {base!r} not in DataFrame columns")
        stats = _native.rolling_stats(
            df[base].to_numpy(dtype=float), steps, ddof=ddof
        )
        for j, w in enumerate(ROLLING_WINDOWS_MIN):
            new_cols[f"{base}_mean_{w}min"] = stats[:, j * 2]
            new_cols[f"{base}_std_{w}min"] = stats[:, j * 2 + 1]
    # One concat, not 64 inserts: avoids pandas block fragmentation.
    return pd.concat(
        [df.copy(), pd.DataFrame(new_cols, index=df.index)], axis=1
    )


def compute_temporal_features(df, timestamp_column: str = None):
    """Add the sin/cos temporal encoding columns from timestamps.

    Uses ``timestamp_column`` if given, else the DataFrame's DatetimeIndex.
    Encodings: minute-of-day / 1440, day-of-week / 7, day-of-month / 31,
    month / 12, each as (sin, cos) of the phase — the cyclic form the
    reference's `temporal_features` names (`config.py`).
    """
    import numpy as np
    import pandas as pd

    # DatetimeIndex either way: a converted Series would need the .dt
    # accessor for .hour/.dayofweek, a DatetimeIndex exposes them directly.
    ts = pd.DatetimeIndex(
        pd.to_datetime(df[timestamp_column])
        if timestamp_column
        else pd.to_datetime(df.index)
    )
    phases = {
        "minute_of_day": (ts.hour * 60 + ts.minute) / 1440.0,
        "day_of_week": ts.dayofweek / 7.0,
        "day_of_month": (ts.day - 1) / 31.0,
        "month": (ts.month - 1) / 12.0,
    }
    out = df.copy()
    for unit, phase in phases.items():
        angle = 2.0 * np.pi * np.asarray(phase, dtype=np.float64)
        out[f"{unit}_sin"] = np.sin(angle).astype(np.float32)
        out[f"{unit}_cos"] = np.cos(angle).astype(np.float32)
    return out


def build_feature_frame(raw_df, channels=SENSOR_CHANNELS,
                        minutes_per_step: int = 1,
                        timestamp_column: str = None):
    """Raw sensor streams -> the full `features` column surface.

    One call takes a DataFrame of raw channels (+ timestamps) to the
    ``len(features)``-column frame (76: 4 channels x (raw + 8 windows x
    mean/std) + 8 temporal encodings) the reference's pipeline selects
    (`ray-tune-hpo-regression.py:18-19,442`), ready for
    ``make_regression_dataset``. Columns are returned in `features` order.
    """
    out = compute_rolling_features(raw_df, channels, minutes_per_step)
    out = compute_temporal_features(out, timestamp_column)
    missing = [c for c in features if c not in out.columns]
    if missing:
        raise KeyError(f"feature columns missing after assembly: {missing}")
    return out[features]
