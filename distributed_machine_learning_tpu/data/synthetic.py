"""Synthetic and public datasets for smoke tests and benchmarks.

* ``dummy_regression_data`` — parity with the reference's `create_dummy_data`
  (`/root/reference/ray-tune-hpo-regression-sample.py:28-55`): random
  ``(1000, 50, 10)`` sequence regression set with an 80/20 split.
* ``glucose_like_data`` — a learnable synthetic stand-in for the wearable
  glucose workload (the real patient ``.npy`` files are private): smooth
  sensor-driven latent + noise, windowed like the real pipeline.
* ``california_housing_data`` — sklearn California Housing (BASELINE.json
  config 1), gated on sklearn availability.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from distributed_machine_learning_tpu.data.loader import Dataset, train_val_split
from distributed_machine_learning_tpu.utils.seeding import rng_from


def dummy_regression_data(
    num_samples: int = 1000,
    seq_len: int = 50,
    num_features: int = 10,
    val_fraction: float = 0.2,
    seed: int = 0,
) -> Tuple[Dataset, Dataset]:
    """Random sequence-regression data in the reference smoke-test shape."""
    rng = rng_from("dummy", seed)
    x = rng.standard_normal((num_samples, seq_len, num_features)).astype(np.float32)
    # Learnable target (not pure noise like the reference): weighted sum of the
    # last few steps, so validation loss actually responds to training.
    w = rng.standard_normal((num_features,)).astype(np.float32)
    y = (x[:, -5:, :] @ w).mean(axis=1, keepdims=True) + 0.1 * rng.standard_normal(
        (num_samples, 1)
    ).astype(np.float32)
    return train_val_split(x, y, val_fraction=val_fraction, seed=seed, shuffle=False)


def glucose_like_data(
    num_steps: int = 20_000,
    num_features: int = 16,
    interval: int = 96,
    stride: int = 96,
    val_fraction: float = 0.3,
    seed: int = 7,
) -> Tuple[Dataset, Dataset]:
    """Windowed synthetic wearable-sensor series with a forecastable glucose target."""
    from distributed_machine_learning_tpu.data.loader import split_into_intervals

    rng = rng_from("glucose", seed)
    t = np.arange(num_steps, dtype=np.float32)
    # Sensor channels: daily/meal-cycle sinusoids + AR noise.
    phases = rng.uniform(0, 2 * np.pi, num_features)
    periods = rng.choice([96.0, 288.0, 1440.0], num_features)
    sensors = np.sin(2 * np.pi * t[:, None] / periods[None, :] + phases[None, :])
    noise = rng.standard_normal((num_steps, num_features)).astype(np.float32)
    for i in range(1, num_steps):  # AR(1) smoothing
        noise[i] = 0.9 * noise[i - 1] + 0.1 * noise[i]
    x = (sensors + 0.5 * noise).astype(np.float32)

    w = rng.standard_normal((num_features,)).astype(np.float32) / np.sqrt(num_features)
    latent = x @ w
    glucose = 120.0 + 30.0 * np.tanh(np.convolve(latent, np.ones(12) / 12, mode="same"))
    glucose = (glucose + rng.standard_normal(num_steps) * 2.0).astype(np.float32)

    xw = split_into_intervals(x, interval, stride)
    yw = split_into_intervals(glucose, interval, stride)[:, -1, 0:1]
    return train_val_split(xw, yw, val_fraction=val_fraction, seed=seed)


def california_housing_data(
    val_fraction: float = 0.25, seed: int = 0
) -> Tuple[Dataset, Dataset]:
    """sklearn California Housing, standardized; falls back to synthetic tabular."""
    try:
        from sklearn.datasets import fetch_california_housing

        bunch = fetch_california_housing()
        x = bunch.data.astype(np.float32)
        y = bunch.target.astype(np.float32)[:, None]
    except Exception:
        x, y = _synthetic_tabular(seed)
    x = (x - x.mean(axis=0)) / (x.std(axis=0) + 1e-8)
    return train_val_split(x, y, val_fraction=val_fraction, seed=seed)


def _synthetic_tabular(seed: int, n: int = 20_000, f: int = 8):
    rng = rng_from("tabular", seed)
    x = rng.standard_normal((n, f)).astype(np.float32)
    w = rng.standard_normal((f,)).astype(np.float32)
    y = (x @ w + 0.3 * np.sin(3 * x[:, 0]) + 0.1 * rng.standard_normal(n)).astype(
        np.float32
    )[:, None]
    return x, y
