"""Out-of-core training: the double-buffered host→device prefetch pipeline.

Every trainable used to stage the full dataset to device memory once
(``Dataset.as_jax`` / ``stage_data`` — "HBM-resident epochs"): a dataset
bigger than one chip's budget could not train at all, and there was zero
host↔device overlap anywhere in the stack.  This module is the loader
underneath ``input_mode="streaming"``:

* a **bounded ring** of device-resident staging slabs (``ChunkPrefetcher``)
  fed by a background **producer thread** — the producer shuffles/gathers
  the next chunk on host (native kernels, ``data/native.py``) and
  ``device_put``\\ s chunk *k+1* while the fused epoch program consumes
  donated chunk *k* (donation frees each consumed slab, so at most
  ``depth + 1`` slabs ever exist on device);
* **engagement policy** (:func:`resolve_input_mode`): explicit
  ``input_mode="resident"|"streaming"`` wins; ``"auto"`` engages streaming
  when the staged dataset would exceed ``streaming_engage_fraction``
  (default 0.5) of :func:`models.flagship.single_chip_hbm_bytes` — on the
  CPU test platform that budget is the ``DML_CPU_DEVICE_BUDGET_BYTES``
  virtual one, which is what makes the out-of-core claim provable in
  tier-1;
* the **determinism contract**: a streaming run sees exactly the batches a
  resident run of the same seed sees, in the same order, and finishes with
  bit-identical params — the producer replays the resident path's own
  permutation (threefry draws are identical eager vs jit) and the chunk
  programs continue the resident epoch scan's PRNG key chain across chunk
  boundaries (``tune/_regression_program.make_chunk_epoch_fn``);
* the **host_input counter family**: prefetch hits, producer/consumer
  waits (count + seconds), chunks/bytes staged, producer stalls/crashes,
  and the derived ``overlap_efficiency = 1 − consumer_wait_s / step time``
  — published to ``experiment_state.json["host_input"]`` and TensorBoard
  ``host_input/*`` by the drivers, asserted by ``bench.py``'s
  ``streaming`` section;
* **failure surfaces**: the producer is watched by the existing liveness
  ``DispatchWatchdog`` (silence past the deadline is counted as
  ``producer_stalls`` while the consumer keeps waiting, and a hard timeout
  turns a wedged producer into an ordinary trial error the retry budget
  handles); ``chaos.FaultPlan(slow_producer_ms=..., producer_crash_at=...)``
  injects degradation and death deterministically.

The dataset-rebuild disk cache (``data/loader.py``) shares this module's
counter registry (``dataset_cache_hits/misses/bytes``).
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu import obs

# A scan's xs slab can never ALIAS an output (the shapes differ), so XLA
# warns that the donated chunk buffers are "not usable" — but donation
# still invalidates and frees each consumed slab at the chunk boundary,
# which is exactly the ring's memory bound.  Expected for every streaming
# chunk program, so it is silenced here (real donation regressions are
# caught by the sharded trainable's is_deleted audit counter, not by this
# warning).
import warnings as _warnings

_warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable",
    category=UserWarning,
)

INPUT_MODES = ("auto", "resident", "streaming")

# "auto" engages streaming when staged bytes exceed this fraction of the
# device budget (params/optimizer/activations need the rest); override
# per-trial via config["streaming_engage_fraction"].
DEFAULT_ENGAGE_FRACTION = 0.5
# Fraction of the device budget the staging ring may occupy across all
# in-flight slabs (depth staged + 1 being consumed).
RING_BUDGET_FRACTION = 0.25
# Ring depth: 2 = classic double buffering (producer stages k+1 while the
# device consumes k).  config["streaming_prefetch_depth"] overrides.
DEFAULT_PREFETCH_DEPTH = 2
# Producer silence past this is a counted stall (liveness watchdog);
# config["streaming_producer_deadline_s"] overrides.
DEFAULT_PRODUCER_DEADLINE_S = 60.0


class ResidentOverBudgetError(RuntimeError):
    """``input_mode="resident"`` asked to stage more bytes than the device
    budget holds.  ``"auto"`` would have engaged streaming; raising (rather
    than OOMing later, or silently streaming against an explicit knob) is
    the budget check the out-of-core acceptance test asserts."""


class ProducerStalled(RuntimeError):
    """The producer thread went silent past the hard timeout.  Surfaced on
    the CONSUMER (trial) thread so the ordinary error path — retry budget,
    checkpoint restore, device release — handles a wedged producer exactly
    like a wedged dispatch."""


# ---------------------------------------------------------------------------
# host_input counter family
# ---------------------------------------------------------------------------


class HostInputCounters:
    """Process-wide counters for the streaming input path (same registry
    discipline as ``compilecache/counters.py``: drivers snapshot at start
    and publish ``delta_since`` at teardown)."""

    _FIELDS = (
        "streams_engaged",       # trainables that ran input_mode=streaming
        "mode_fallbacks",        # streaming requested but driver fell back
        "chunks_staged",
        "bytes_staged",
        "prefetch_hits",         # consumer asked, chunk was already staged
        "consumer_waits",        # consumer had to wait on the producer
        "consumer_wait_s",
        "producer_waits",        # producer blocked on a full ring
        "producer_wait_s",
        "consume_s",             # consumer seconds spent in chunk programs
        "producer_stalls",       # liveness watchdog expiries on the producer
        "producer_crashes",
        # Dataset-rebuild disk cache (data/loader.py): windowed/standardized
        # arrays reopened via np.load(mmap_mode="r") instead of re-windowed.
        "dataset_cache_hits",
        "dataset_cache_misses",
        "dataset_cache_bytes",
    )

    def __init__(self):
        self._lock = named_lock("data.host_input_counters")
        self._c: Dict[str, float] = {k: 0 for k in self._FIELDS}

    def add(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + value

    def get(self, name: str) -> float:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self._c.items()
            }

    def delta_since(self, baseline: Dict[str, float]) -> Dict[str, float]:
        snap = self.snapshot()
        return {k: round(v - baseline.get(k, 0), 4) for k, v in snap.items()}

    def reset(self) -> None:
        """Test hook: zero every counter."""
        with self._lock:
            self._c = {k: 0 for k in self._FIELDS}


_counters = HostInputCounters()

# Same counters, one more consumer: the unified metrics registry
# (obs/registry.py) — the driver-published host_input block is unchanged.
from distributed_machine_learning_tpu.obs.registry import (  # noqa: E402
    get_registry as _obs_registry,
)

_obs_registry().register_family("host_input", _counters)


def get_host_input_counters() -> HostInputCounters:
    """The process-wide registry (one per process)."""
    return _counters


def overlap_efficiency(counters: Dict[str, float]) -> Optional[float]:
    """``1 − consumer_wait_s / step time``: the fraction of consumer step
    time NOT spent waiting on host input.  1.0 = the device never waited
    (perfect overlap); None when nothing streamed."""
    step_s = float(counters.get("consume_s", 0) or 0)
    wait_s = float(counters.get("consumer_wait_s", 0) or 0)
    if step_s <= 0 and wait_s <= 0:
        return None
    return round(max(0.0, 1.0 - wait_s / max(step_s + wait_s, 1e-9)), 4)


def host_input_block(baseline: Dict[str, float]) -> Optional[Dict[str, Any]]:
    """The ``experiment_state.json["host_input"]`` block for one run: the
    counter deltas plus the derived overlap efficiency; None when the run
    neither streamed nor touched the dataset cache."""
    delta = _counters.delta_since(baseline)
    if not any(delta.values()):
        return None
    eff = overlap_efficiency(delta)
    if eff is not None:
        delta["overlap_efficiency"] = eff
    return delta


# ---------------------------------------------------------------------------
# engagement policy / budget check
# ---------------------------------------------------------------------------


def staged_nbytes(train_data, val_data, compute_dtype) -> int:
    """Bytes resident staging would pin on ONE device: x splits in the
    compute dtype, y splits in float32 (``stage_data``'s layout)."""
    x_item = int(np.dtype(compute_dtype).itemsize) if compute_dtype else 4
    total = 0
    for ds in (train_data, val_data):
        if ds is None:
            continue
        total += int(ds.x.size) * x_item + int(ds.y.size) * 4
    return total


def device_budget_bytes(device=None) -> int:
    """One device's accelerator-memory budget (virtual on CPU — see
    ``models/flagship.single_chip_hbm_bytes``)."""
    from distributed_machine_learning_tpu.models.flagship import (
        single_chip_hbm_bytes,
    )

    return single_chip_hbm_bytes(device)


def check_resident_budget(nbytes: int, device=None, what: str = "dataset"):
    """Raise :class:`ResidentOverBudgetError` when ``nbytes`` exceeds the
    device budget — the check resident staging (``Dataset.as_jax`` /
    ``stage_data``) provably fails for an over-budget dataset."""
    budget = device_budget_bytes(device)
    if nbytes > budget:
        raise ResidentOverBudgetError(
            f"resident staging of {what} needs {nbytes} bytes but the "
            f"device budget is {budget} bytes "
            f"({getattr(device, 'platform', 'cpu')}; on CPU the virtual "
            f"DML_CPU_DEVICE_BUDGET_BYTES budget applies) — use "
            f'input_mode="streaming" (or "auto") to train out-of-core'
        )
    return budget


def resolve_input_mode(
    config: Dict[str, Any],
    nbytes: int,
    device=None,
    *,
    shards: int = 1,
) -> str:
    """Resolve ``config["input_mode"]`` to ``"resident"`` or ``"streaming"``.

    ``shards``: how many devices the staged arrays' batch axis spreads over
    (the sharded trainable's dp degree) — resident bytes PER DEVICE are
    ``nbytes / shards``.  Explicit ``"resident"`` over budget raises;
    ``"auto"`` engages streaming past ``streaming_engage_fraction`` of the
    budget; explicit ``"streaming"`` always streams (the parity tests force
    it on small datasets).
    """
    mode = str(config.get("input_mode", "auto") or "auto").lower()
    if mode not in INPUT_MODES:
        raise ValueError(
            f"input_mode must be one of {INPUT_MODES}, got {mode!r}"
        )
    per_device = int(nbytes) // max(int(shards), 1)
    if mode == "streaming":
        return "streaming"
    if mode == "resident":
        check_resident_budget(per_device, device, what="the dataset")
        return "resident"
    fraction = float(
        config.get("streaming_engage_fraction", DEFAULT_ENGAGE_FRACTION)
    )
    if per_device > fraction * device_budget_bytes(device):
        return "streaming"
    return "resident"


# ---------------------------------------------------------------------------
# chunk planning
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ChunkPlan:
    """How one epoch's batch sequence splits into staged chunks.

    ``num_chunks`` full chunks of ``chunk_batches`` batches, plus an
    optional tail of ``tail_batches`` — the tail compiles its own (second)
    chunk program; the chunk COUNT never shapes a trace (the host loops),
    which is why the compile-cache key folds in rows only
    (``compilecache.chunked_program_key``)."""

    batch_size: int
    num_batches: int       # batches per epoch (= optimizer steps per epoch)
    chunk_batches: int     # batches per full chunk
    num_chunks: int        # full chunks per epoch
    tail_batches: int      # 0, or the last chunk's (smaller) batch count

    @property
    def chunks_per_epoch(self) -> int:
        return self.num_chunks + (1 if self.tail_batches else 0)

    def chunk_sizes(self) -> Iterator[Tuple[int, int]]:
        """Yield ``(start_batch, rows)`` per chunk, in epoch order."""
        for c in range(self.num_chunks):
            yield c * self.chunk_batches, self.chunk_batches
        if self.tail_batches:
            yield self.num_chunks * self.chunk_batches, self.tail_batches


def plan_chunks(
    num_batches: int,
    batch_size: int,
    row_nbytes: int,
    *,
    device=None,
    config: Optional[Dict[str, Any]] = None,
) -> ChunkPlan:
    """Size chunks so the whole ring fits ``RING_BUDGET_FRACTION`` of the
    device budget: per-slab bytes = ring budget / (depth + 1) — depth
    staged slabs plus the one being consumed (donation frees it at the
    chunk boundary).  ``config["streaming_chunk_batches"]`` overrides."""
    config = config or {}
    depth = int(config.get("streaming_prefetch_depth", DEFAULT_PREFETCH_DEPTH))
    override = config.get("streaming_chunk_batches")
    if override:
        chunk_batches = max(1, min(int(override), num_batches))
    else:
        bytes_per_batch = max(int(batch_size) * int(row_nbytes), 1)
        ring_budget = RING_BUDGET_FRACTION * device_budget_bytes(device)
        per_slab = ring_budget / (depth + 1)
        chunk_batches = int(
            max(1, min(per_slab // bytes_per_batch, num_batches))
        )
    return ChunkPlan(
        batch_size=int(batch_size),
        num_batches=int(num_batches),
        chunk_batches=chunk_batches,
        num_chunks=int(num_batches) // chunk_batches,
        tail_batches=int(num_batches) % chunk_batches,
    )


def prefetch_depth(config: Optional[Dict[str, Any]] = None) -> int:
    return int(
        (config or {}).get(
            "streaming_prefetch_depth", DEFAULT_PREFETCH_DEPTH
        )
    )


def gather_batches(
    x: np.ndarray, y: np.ndarray, idx: np.ndarray, rows: int, batch_size: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-gather ``idx`` (flat, ``rows * batch_size`` long) out of the
    source arrays into ``[rows, batch_size, ...]`` slabs — the native
    OpenMP gather when both splits are float32 (the same kernel
    ``Dataset.batches`` uses), fancy indexing otherwise."""
    from distributed_machine_learning_tpu.data import native as _native

    if x.dtype == np.float32 and y.dtype == np.float32:
        xg, yg = _native.gather(x, idx), _native.gather(y, idx)
    else:
        xg, yg = x[idx], y[idx]
    return (
        xg.reshape(rows, batch_size, *x.shape[1:]),
        yg.reshape(rows, batch_size, *y.shape[1:]),
    )


# ---------------------------------------------------------------------------
# the prefetch ring
# ---------------------------------------------------------------------------

_DONE = object()


class _Crash:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class ChunkPrefetcher:
    """A bounded ring of staged device slabs fed by a producer thread.

    ``source`` is a generator whose ``next()`` performs the host work AND
    the ``device_put`` for one chunk, returning the staged item (any
    pytree-ish value; items with ``nbytes`` attributes are accounted as
    staged bytes).  The producer thread pulls from it and feeds the
    bounded ring (``maxsize=depth``); the consumer (trial thread) calls
    :meth:`get` per chunk.  A chunk already in the ring is a
    ``prefetch_hit``; an empty ring is a counted consumer wait — overlap
    efficiency falls out of exactly these counters.

    The producer is watched by a liveness ``DispatchWatchdog``: one beat
    per staged chunk, expiry counted as ``producer_stalls`` while the
    consumer keeps waiting, and :class:`ProducerStalled` raised on the
    consumer thread past ``hard_timeout_s`` so a wedged producer follows
    the ordinary trial error path.  A producer exception (including the
    chaos-injected crash) is re-raised on the consumer thread.
    """

    def __init__(
        self,
        source: Iterator[Any],
        *,
        depth: int = DEFAULT_PREFETCH_DEPTH,
        deadline_s: float = DEFAULT_PRODUCER_DEADLINE_S,
        hard_timeout_s: Optional[float] = None,
        name: str = "host-input",
        counters: Optional[HostInputCounters] = None,
    ):
        from distributed_machine_learning_tpu.liveness import DispatchWatchdog

        self._source = source
        self._depth = max(int(depth), 1)
        self._ring: "queue.Queue" = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._counters = counters or get_host_input_counters()
        self._deadline_s = float(deadline_s)
        self._hard_timeout_s = (
            float(hard_timeout_s)
            if hard_timeout_s is not None
            else max(8.0 * self._deadline_s, 30.0)
        )
        # Polled from the consumer's wait loop — no monitor thread needed.
        self._watchdog = DispatchWatchdog(self._deadline_s)
        self._watchdog.track("producer", info=name)
        self._name = name
        self._producer = threading.Thread(
            target=self._produce, name=f"{name}-producer", daemon=True
        )
        self._chunk_index = 0
        # Per-instance consumer wait seconds (the registry is process-wide
        # and concurrent trials share it; per-epoch overlap accounting
        # needs THIS ring's waits).
        self.wait_s = 0.0
        self._producer.start()

    # -- producer side -------------------------------------------------------

    def _put(self, item) -> bool:
        """Ring put with wait accounting; False when closing."""
        waited = False
        t0 = time.monotonic()
        while not self._stop.is_set():
            try:
                self._ring.put(item, timeout=0.05)
                if waited:
                    self._counters.add(
                        "producer_wait_s", time.monotonic() - t0
                    )
                return True
            except queue.Full:
                if not waited:
                    waited = True
                    self._counters.add("producer_waits")
        return False

    def _produce(self) -> None:
        from distributed_machine_learning_tpu import chaos

        try:
            while not self._stop.is_set():
                plan = chaos.active_plan()
                if plan is not None:
                    # Deterministic degradation/death: sleep per chunk
                    # and/or crash at a scheduled chunk index.  The ring
                    # name ("stream-<trial_id>") lets a plan slow ONE
                    # trial's producer — the named-straggler fault.
                    plan.maybe_producer_fault(
                        self._chunk_index, name=self._name
                    )
                try:
                    with obs.span(
                        "prefetch.stage", {"chunk": self._chunk_index}
                    ):
                        item = next(self._source)
                except StopIteration:
                    self._put(_DONE)
                    return
                self._chunk_index += 1
                self._counters.add("chunks_staged")
                self._counters.add("bytes_staged", _item_nbytes(item))
                if not self._put(item):
                    return
                self._watchdog.beat("producer")
        except BaseException as exc:  # noqa: BLE001 - re-raised on consumer
            self._counters.add("producer_crashes")
            self._put(_Crash(exc))

    # -- consumer side -------------------------------------------------------

    def get(self):
        """Next staged chunk; raises the producer's exception on crash,
        :class:`ProducerStalled` past the hard timeout, ``StopIteration``
        when the source is exhausted."""
        try:
            item = self._ring.get_nowait()
            self._counters.add("prefetch_hits")
        except queue.Empty:
            self._counters.add("consumer_waits")
            t0 = time.monotonic()
            item = None
            with obs.span("prefetch.wait", {"chunk": self._chunk_index}):
                while item is None:
                    waited = time.monotonic() - t0
                    if waited > self._hard_timeout_s:
                        self._counters.add("consumer_wait_s", waited)
                        self.wait_s += waited
                        obs.event(
                            "producer_stalled",
                            {"waited_s": round(waited, 2)},
                        )
                        raise ProducerStalled(
                            f"host-input producer silent for {waited:.1f}s "
                            f"(hard timeout {self._hard_timeout_s:.1f}s, "
                            f"stall deadline {self._deadline_s:.1f}s)"
                        )
                    # Silence past the deadline is a counted liveness event
                    # (edge-triggered: once per stall episode) — the
                    # operator signal that the producer, not the device, is
                    # the bottleneck or the casualty.
                    for _ in self._watchdog.expired():
                        self._counters.add("producer_stalls")
                    try:
                        item = self._ring.get(timeout=0.05)
                    except queue.Empty:
                        continue
            waited = time.monotonic() - t0
            self._counters.add("consumer_wait_s", waited)
            self.wait_s += waited
        if isinstance(item, _Crash):
            raise item.exc
        if item is _DONE:
            raise StopIteration
        return item

    def note_consume(self, seconds: float) -> None:
        """Record consumer seconds spent executing chunk programs (the
        denominator of overlap efficiency)."""
        self._counters.add("consume_s", float(seconds))

    def close(self) -> None:
        """Stop the producer and drain the ring (idempotent)."""
        self._stop.set()
        try:
            while True:
                self._ring.get_nowait()
        except queue.Empty:
            pass
        if self._producer.is_alive():
            self._producer.join(timeout=2.0)
        self._watchdog.untrack("producer")

    def __enter__(self) -> "ChunkPrefetcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def _item_nbytes(item) -> int:
    """Total nbytes across array leaves of a staged item (tuples/lists/
    dicts of arrays; anything without ``nbytes`` counts 0)."""
    if isinstance(item, (tuple, list)):
        return sum(_item_nbytes(v) for v in item)
    if isinstance(item, dict):
        return sum(_item_nbytes(v) for v in item.values())
    return int(getattr(item, "nbytes", 0) or 0)


# ---------------------------------------------------------------------------
# streaming program cache (unsharded trainable)
# ---------------------------------------------------------------------------

# One built+jitted streaming program set per chunked program key: under
# injected hyperparameters the chunk programs are trial-independent, so a
# cohort of streaming trials traces each chunk program once (the same
# rationale as tune/trainable.py's cohort bundle cache — but nothing here
# pins staged data, so the cap is entry-count only).
_STREAM_CACHE: Dict[str, Any] = {}
_STREAM_LOCKS: Dict[str, Any] = {}
_STREAM_CACHE_MAX = 8
_STREAM_GUARD = named_lock("data.stream_program_guard")


def clear_stream_program_cache() -> None:
    with _STREAM_GUARD:
        _STREAM_CACHE.clear()
        _STREAM_LOCKS.clear()


def stream_bundle_for(key: str, build: Callable[[], Any]):
    """Exactly-once build of a streaming program bundle per key (the
    cohort's other trials wait on the per-key lock and reuse)."""
    with _STREAM_GUARD:
        bundle = _STREAM_CACHE.pop(key, None)
        if bundle is not None:
            _STREAM_CACHE[key] = bundle  # LRU touch
            return bundle
        lock = _STREAM_LOCKS.setdefault(key, named_lock("data.stream_build"))
    with lock:
        with _STREAM_GUARD:
            bundle = _STREAM_CACHE.get(key)
            if bundle is not None:
                return bundle
        bundle = build()
        with _STREAM_GUARD:
            _STREAM_CACHE[key] = bundle
            while len(_STREAM_CACHE) > _STREAM_CACHE_MAX:
                evicted = next(iter(_STREAM_CACHE))
                _STREAM_CACHE.pop(evicted)
                _STREAM_LOCKS.pop(evicted, None)
        return bundle
