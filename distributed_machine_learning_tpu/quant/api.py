# dmlint-scope: quant-path
"""Bundle-level quantization: f32 servable bundle -> quantized sibling.

``export_bundle(precision=...)`` quantizes at export time; this module is
the second entry point — re-quantizing a bundle that already shipped
(the fleet-migration path: the f32 parent keeps serving while its int8
sibling is exported, calibrated, and ``hot_swap``-promoted)."""

from __future__ import annotations

from typing import Any, Dict

from distributed_machine_learning_tpu.quant import calibrate as _cal
from distributed_machine_learning_tpu.quant.core import (
    check_precision,
    quantize_variables,
)


def quantize_bundle(
    bundle_dir: str,
    out_dir: str,
    precision: str,
    calibration_batch,
) -> str:
    """Load the f32 bundle at ``bundle_dir``, quantize to ``precision``,
    calibrate on ``calibration_batch``, and write a sibling bundle to
    ``out_dir`` (same manifest lineage, ``precision`` + ``quant`` block
    updated, ``source.parent_bundle`` recording provenance).  Returns
    ``out_dir``."""
    from distributed_machine_learning_tpu.serve import export as _export

    check_precision(precision)
    if precision == "f32":
        raise ValueError(
            "quantize_bundle targets bf16/int8; the f32 parent already "
            "exists"
        )
    bundle = _export.load_bundle(bundle_dir)
    parent_precision = bundle.precision
    if parent_precision != "f32":
        raise ValueError(
            f"bundle at {bundle_dir!r} is already {parent_precision} — "
            f"quantize from the f32 parent, not a quantized sibling"
        )
    model = bundle.build_model()
    quant_block = build_quant_block(
        model, bundle.variables, precision, calibration_batch
    )
    qvariables = quant_block.pop("_variables")
    manifest = dict(bundle.manifest)
    manifest["precision"] = precision
    manifest["quant"] = quant_block
    source = dict(manifest.get("source") or {})
    source["parent_bundle"] = bundle_dir
    manifest["source"] = source
    _export.write_bundle(out_dir, manifest, qvariables)
    return out_dir


def build_quant_block(
    model,
    f32_variables: Dict[str, Any],
    precision: str,
    calibration_batch,
) -> Dict[str, Any]:
    """Quantize + calibrate: returns the manifest ``quant`` block with the
    quantized variables tree riding under the private ``_variables`` key
    (popped by the caller before the block is serialized)."""
    if calibration_batch is None:
        raise ValueError(
            f"precision={precision!r} requires a calibration_batch — the "
            f"manifest's quality delta is measured, never assumed"
        )
    qvariables, stats = quantize_variables(f32_variables, precision)
    calibration = _cal.calibrate(
        model, f32_variables, qvariables, calibration_batch, precision
    )
    block: Dict[str, Any] = {
        "method": stats["method"],
        "parent_precision": "f32",
        "quantized_leaves": stats["quantized_leaves"],
        "total_leaves": stats["total_leaves"],
        "bytes_f32": stats["bytes_f32"],
        "bytes_quant": stats["bytes_quant"],
        "scales": stats["scales"],
        "calibration": calibration,
        "quality_delta_mape": calibration["quality_delta_mape"],
        "_variables": qvariables,
    }
    if "compression" in stats:
        block["compression"] = stats["compression"]
    return block


__all__ = ["quantize_bundle", "build_quant_block"]
