# dmlint-scope: quant-path
"""Post-training weight quantization: symmetric per-channel int8 + bf16.

The serving-economics lever the Gemma study (PAPERS.md) names: weights
dominate a small model's memory traffic, so shrinking them 4x (int8) or
2x (bf16) moves the inference program toward the bandwidth roof the perf
observatory's ``roofline()`` measures.  Everything here is *post-training*
— no quantization-aware training, no optimizer state — so it composes
with any checkpoint ``tune`` already wrote.

Quantization scheme (int8):

* per-channel symmetric — one scale per output channel (the LAST axis of
  a >=2-d weight), ``scale = max|w| / 127`` reduced over every other
  axis; values round-to-nearest into ``[-127, 127]`` (the -128 code is
  unused so the grid is symmetric around zero);
* sub-2-d leaves (biases, layer-norm gains, scalars) stay f32 — they are
  a rounding error of the byte budget and the cheapest accuracy insurance
  there is;
* scales ride next to the weights in the bundle's msgpack under the
  ``quant_scales`` collection, mirroring the params tree structure for
  the quantized leaves only.

Dequantization happens INSIDE the jitted inference program (XLA fuses the
int8->bf16 cast + scale multiply into the consuming matmul), with bf16
accumulation and one f32 cast on the way out.  Every float32-promoting
cast in the quantized path lives in a ``dequant*``-named helper below —
the designated sites dmlint's DML018 (implicit-upcast-in-quantized-path)
exempts; an f32 upcast anywhere else in ``quant/`` or ``serve/engine.py``
silently re-inflates the memory traffic the quantization paid for.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any, Dict, Tuple

import numpy as np

PRECISIONS = ("f32", "bf16", "int8")

# Symmetric int8 grid: [-127, 127], -128 unused.
_QMAX = 127.0

# Per-leaf scale summaries in the manifest are bounded — a transformer has
# hundreds of leaves and the manifest must stay human-readable.
_SCALE_SUMMARY_MAX = 16


def check_precision(precision: str) -> str:
    if precision not in PRECISIONS:
        raise ValueError(
            f"precision must be one of {PRECISIONS}, got {precision!r}"
        )
    return precision


def _bf16_dtype():
    import jax.numpy as jnp

    return jnp.bfloat16


def quantize_leaf(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """One >=2-d weight -> ``(q_int8, scale_f32)`` with a per-out-channel
    scale (reduced over all axes but the last, keepdims so the dequant
    multiply broadcasts with no reshape)."""
    w = np.asarray(w)
    axes = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=axes, keepdims=True)
    scale = np.where(amax > 0, amax, 1.0) / _QMAX
    scale = np.asarray(scale, dtype=w.dtype)
    q = np.clip(np.rint(w / scale), -_QMAX, _QMAX).astype(np.int8)
    return q, scale


def quantizable(leaf: Any) -> bool:
    """int8 targets: >=2-d floating leaves (matmul weights / embeddings)."""
    arr = np.asarray(leaf)
    return arr.ndim >= 2 and np.issubdtype(arr.dtype, np.floating)


def quantize_params(
    params: Dict[str, Any], precision: str
) -> Tuple[Dict[str, Any], Dict[str, Any], Dict[str, Any]]:
    """Quantize a (host) params tree -> ``(qparams, scales, stats)``.

    ``scales`` mirrors the tree structure for quantized leaves only (int8;
    empty for bf16 — a straight cast has no side table).  ``stats`` is the
    manifest-ready summary: leaf counts, byte budgets, and a bounded
    per-leaf scale digest.
    """
    check_precision(precision)
    stats: Dict[str, Any] = {
        "method": (
            "symmetric-per-channel" if precision == "int8" else "cast"
        ),
        "quantized_leaves": 0,
        "total_leaves": 0,
        "bytes_f32": 0,
        "bytes_quant": 0,
        "scales": {},
    }
    if precision == "f32":
        return params, {}, stats

    def walk(node, path):
        if isinstance(node, Mapping):
            q, s = {}, {}
            for k, v in node.items():
                qk, sk = walk(v, path + (k,))
                q[k] = qk
                if sk is not None:
                    s[k] = sk
            return q, (s or None)
        leaf = np.asarray(node)
        stats["total_leaves"] += 1
        stats["bytes_f32"] += int(leaf.nbytes)
        if precision == "bf16":
            if np.issubdtype(leaf.dtype, np.floating):
                out = leaf.astype(_bf16_dtype())
                stats["quantized_leaves"] += 1
                stats["bytes_quant"] += int(out.nbytes)
                return out, None
            stats["bytes_quant"] += int(leaf.nbytes)
            return leaf, None
        if not quantizable(leaf):
            stats["bytes_quant"] += int(leaf.nbytes)
            return leaf, None
        q, scale = quantize_leaf(leaf)
        stats["quantized_leaves"] += 1
        stats["bytes_quant"] += int(q.nbytes) + int(scale.nbytes)
        if len(stats["scales"]) < _SCALE_SUMMARY_MAX:
            stats["scales"]["/".join(path)] = {
                "shape": list(leaf.shape),
                "scale_min": float(scale.min()),
                "scale_max": float(scale.max()),
                "scale_mean": float(scale.mean()),
            }
        return q, scale

    qparams, scales = walk(params, ())
    if stats["bytes_f32"]:
        stats["compression"] = round(
            stats["bytes_f32"] / max(stats["bytes_quant"], 1), 3
        )
    return qparams, (scales or {}), stats


def quantize_variables(
    variables: Dict[str, Any], precision: str
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Quantize a full variables dict ``{"params": .., ["batch_stats": ..]}``
    -> ``(qvariables, stats)``.

    The quantized tree gains a ``quant_scales`` collection (int8 only);
    ``batch_stats`` stay f32 — they are tiny running moments, and norm
    statistics are exactly where rounding hurts most.
    """
    check_precision(precision)
    qparams, scales, stats = quantize_params(variables["params"], precision)
    out = dict(variables)
    out["params"] = qparams
    if scales:
        out["quant_scales"] = scales
    return out, stats


# -- designated dequant sites (DML018 exemption by name) ---------------------


def dequantize_leaf(q, scale):
    """int8 codes * per-channel scale -> bf16 weight (fused into the
    consuming matmul by XLA; bf16 is the accumulation dtype)."""
    import jax.numpy as jnp

    return q.astype(jnp.bfloat16) * jnp.asarray(scale).astype(jnp.bfloat16)


def dequantize_params(params, scales):
    """Rebuild a compute-ready (bf16) params tree from quantized leaves;
    unquantized leaves downcast to the same compute dtype."""
    import jax.numpy as jnp

    def walk(node, snode):
        if isinstance(node, Mapping):
            return {
                k: walk(v, (snode or {}).get(k)) for k, v in node.items()
            }
        if str(getattr(node, "dtype", "")) == "int8":
            if snode is None:
                raise ValueError(
                    "int8 leaf with no matching entry in quant_scales — "
                    "bundle params and scales are out of sync"
                )
            return dequantize_leaf(node, snode)
        return node.astype(jnp.bfloat16)

    return walk(params, scales)


def dequantize_variables(variables, precision: str):
    """The single entry the inference program calls: quantized storage
    tree -> compute-dtype variables (``quant_scales`` consumed, not
    forwarded to ``model.apply``)."""
    import jax.numpy as jnp

    check_precision(precision)
    if precision == "f32":
        return {k: v for k, v in variables.items() if k != "quant_scales"}
    out = {
        "params": dequantize_params(
            variables["params"], variables.get("quant_scales") or {}
        )
    }
    for coll, tree in variables.items():
        if coll in ("params", "quant_scales"):
            continue
        # Running statistics (batch_stats) join the compute dtype so the
        # normalization arithmetic stays in one precision.
        out[coll] = _tree_astype(tree, jnp.bfloat16)
    return out


def dequantize_output(y):
    """The one sanctioned f32 upcast on the serving path: bf16 program
    output -> f32 answer for the client."""
    import jax.numpy as jnp

    return y.astype(jnp.float32)


def cast_input(x, precision: str):
    """Inputs join the compute dtype (bf16) for quantized programs — a
    downcast, so it lives outside the dequant exemption on purpose."""
    import jax.numpy as jnp

    if precision == "f32":
        return x
    return x.astype(jnp.bfloat16)


def _tree_astype(tree, dtype):
    if isinstance(tree, Mapping):
        return {k: _tree_astype(v, dtype) for k, v in tree.items()}
    return tree.astype(dtype)


# -- fake-quant (quantize -> dequantize round trip, f32 in / f32 out) --------


def fake_quant_tree(params: Dict[str, Any]) -> Dict[str, Any]:
    """Host-side int8 round trip of a single-model params tree: the f32
    weights a model would effectively serve with after int8 export.
    Dtypes are unchanged (f32 in, f32 out), so evaluating with the result
    reuses the caller's already-compiled eval program."""

    def walk(node):
        if isinstance(node, Mapping):
            return {k: walk(v) for k, v in node.items()}
        leaf = np.asarray(node)
        if not quantizable(leaf):
            return leaf
        q, scale = quantize_leaf(leaf)
        return (q.astype(leaf.dtype) * scale).astype(leaf.dtype)

    return walk(params)


def fake_quant_population(params: Dict[str, Any]) -> Dict[str, Any]:
    """``fake_quant_tree`` for population-stacked trees (leading axis =
    population row): per-(row, out-channel) scales, so each row is
    quantized exactly as its own int8 export would be.  Used by the PBT
    ``quality_after_quant`` objective."""

    def walk(node):
        if isinstance(node, Mapping):
            return {k: walk(v) for k, v in node.items()}
        leaf = np.asarray(node)
        # Row axis + a >=2-d weight => ndim >= 3; row-wise biases stay f32.
        if leaf.ndim < 3 or not np.issubdtype(leaf.dtype, np.floating):
            return leaf
        axes = tuple(range(1, leaf.ndim - 1))
        amax = np.max(np.abs(leaf), axis=axes, keepdims=True)
        scale = np.asarray(
            np.where(amax > 0, amax, 1.0) / _QMAX, dtype=leaf.dtype
        )
        q = np.clip(np.rint(leaf / scale), -_QMAX, _QMAX)
        return (q * scale).astype(leaf.dtype)

    return walk(params)


def tree_precision(variables: Dict[str, Any]) -> str:
    """Infer the storage precision of a loaded variables tree (the
    manifest is authoritative; this is the cross-check)."""
    dtypes = set()

    def walk(node):
        if isinstance(node, Mapping):
            for v in node.values():
                walk(v)
            return
        dtypes.add(str(np.asarray(node).dtype))

    walk(variables.get("params", {}))
    if "int8" in dtypes:
        return "int8"
    if "bfloat16" in dtypes:
        return "bf16"
    return "f32"


def leaf_count(tree: Any) -> int:
    if isinstance(tree, Mapping):
        return sum(leaf_count(v) for v in tree.values())
    return 1


__all__ = [
    "PRECISIONS",
    "check_precision",
    "quantize_leaf",
    "quantize_params",
    "quantize_variables",
    "quantizable",
    "dequantize_leaf",
    "dequantize_params",
    "dequantize_variables",
    "dequantize_output",
    "cast_input",
    "fake_quant_tree",
    "fake_quant_population",
    "tree_precision",
    "leaf_count",
]
