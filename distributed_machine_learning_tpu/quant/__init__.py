# dmlint-scope: quant-path
"""Post-training quantization for the serving plane (ROADMAP item 1).

``quant/`` turns a sweep winner's f32 bundle into a cheaper-to-serve
sibling with *measured* quality evidence:

* :mod:`~distributed_machine_learning_tpu.quant.core` — symmetric
  per-channel int8 / bf16 weight quantization, plus the designated
  ``dequant*`` helpers the compiled inference path calls (the only
  sanctioned f32 upcasts per dmlint DML018);
* :mod:`~distributed_machine_learning_tpu.quant.calibrate` — the
  export-time calibration pass: activation ranges + quality delta (MAPE
  vs the f32 parent on a held-out batch), recorded in the manifest;
* :mod:`~distributed_machine_learning_tpu.quant.api` — quantize an
  already-exported bundle (the fleet-migration entry point
  ``examples/serve_quantized.py`` walks).

See docs/performance.md "Quantized serving" for the promotion runbook.
"""

from distributed_machine_learning_tpu.quant.api import (
    build_quant_block,
    quantize_bundle,
)
from distributed_machine_learning_tpu.quant.calibrate import (
    activation_ranges,
    calibrate,
    predict_f32,
    predict_quantized,
    quality_delta,
)
from distributed_machine_learning_tpu.quant.core import (
    PRECISIONS,
    cast_input,
    check_precision,
    dequantize_leaf,
    dequantize_output,
    dequantize_params,
    dequantize_variables,
    fake_quant_population,
    fake_quant_tree,
    quantizable,
    quantize_leaf,
    quantize_params,
    quantize_variables,
    tree_precision,
)

__all__ = [
    "PRECISIONS",
    "activation_ranges",
    "build_quant_block",
    "calibrate",
    "cast_input",
    "check_precision",
    "dequantize_leaf",
    "dequantize_output",
    "dequantize_params",
    "dequantize_variables",
    "fake_quant_population",
    "fake_quant_tree",
    "predict_f32",
    "predict_quantized",
    "quality_delta",
    "quantizable",
    "quantize_bundle",
    "quantize_leaf",
    "quantize_params",
    "quantize_variables",
    "tree_precision",
]
