# dmlint-scope: quant-path
"""Calibration: measure what quantization costs BEFORE promoting it.

A quantized bundle's manifest must carry evidence, not faith — the
promotion runbook reads ``quality_delta_mape`` off ``/metrics`` and
decides from a number that was *measured at export time* on a held-out
calibration batch:

* f32 predictions and quantized predictions over the same batch ->
  MAPE/MAE of the quantized answers against the f32 parent's (labels are
  not required: the question is "does int8 change the answers", not "is
  the model good" — the sweep already answered that);
* per-layer activation ranges (max|activation| via flax intermediate
  capture) — the saturation diagnostic: an activation whose range dwarfs
  its weights' is where symmetric int8 clips first.

Everything runs eagerly on host-sized batches; the calibration pass adds
no compiled program to any cache.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from distributed_machine_learning_tpu.quant.core import (
    cast_input,
    check_precision,
    dequantize_output,
    dequantize_variables,
)

# Activation-range tables in the manifest are bounded like scale digests.
_RANGE_SUMMARY_MAX = 32

_MAPE_EPS = 1e-8


def eval_flag(model) -> str:
    """The model's eval-mode kwarg (``deterministic=True`` vs
    ``train=False``) — the same signature probe ``serve.engine`` uses."""
    import inspect

    try:
        params = inspect.signature(type(model).__call__).parameters
    except (TypeError, ValueError):
        params = {}
    return "train" if (
        "train" in params and "deterministic" not in params
    ) else "deterministic"


def _eval_kwargs(model) -> Dict[str, Any]:
    flag = eval_flag(model)
    return {flag: flag == "deterministic"}


def predict_f32(model, variables, x) -> np.ndarray:
    """Reference predictions with the unquantized variables."""
    y = model.apply(variables, np.asarray(x), **_eval_kwargs(model))
    return np.asarray(y)


def predict_quantized(model, qvariables, x, precision: str) -> np.ndarray:
    """Predictions through the SAME dequant-fused path the serving engine
    compiles (storage tree -> bf16 compute -> f32 out), run eagerly."""
    check_precision(precision)
    fvars = dequantize_variables(qvariables, precision)
    y = model.apply(
        fvars, cast_input(np.asarray(x), precision), **_eval_kwargs(model)
    )
    return np.asarray(dequantize_output(y))


def quality_delta(f32_pred, quant_pred) -> Dict[str, float]:
    """MAPE/MAE of quantized predictions against the f32 parent's."""
    f = np.asarray(f32_pred, dtype=np.float64).ravel()
    q = np.asarray(quant_pred, dtype=np.float64).ravel()
    if f.shape != q.shape:
        raise ValueError(
            f"prediction shapes diverge: f32 {f.shape} vs quant {q.shape}"
        )
    err = np.abs(q - f)
    return {
        "mape": float(np.mean(err / (np.abs(f) + _MAPE_EPS))),
        "mae": float(np.mean(err)),
        "max_abs_err": float(np.max(err)) if err.size else 0.0,
    }


def activation_ranges(model, variables, x) -> Dict[str, float]:
    """Per-layer max|activation| over the calibration batch, bounded to
    the first ``_RANGE_SUMMARY_MAX`` paths (module definition order).
    Best-effort: a model family without intermediate capture support
    yields an empty table, never a failed export."""
    try:
        _, state = model.apply(
            variables,
            np.asarray(x),
            capture_intermediates=True,
            mutable=["intermediates"],
            **_eval_kwargs(model),
        )
    except Exception:  # noqa: BLE001 - diagnostics must not block export
        return {}
    ranges: Dict[str, float] = {}

    def walk(node, path):
        if len(ranges) >= _RANGE_SUMMARY_MAX:
            return
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (k,))
            return
        if isinstance(node, (tuple, list)):
            for v in node:
                walk(v, path)
            return
        arr = np.asarray(node)
        if arr.size:
            key = "/".join(p for p in path if p != "__call__") or "output"
            ranges[key] = max(
                ranges.get(key, 0.0), float(np.max(np.abs(arr)))
            )

    walk(dict(state.get("intermediates", {})), ())
    return ranges


def calibrate(
    model,
    f32_variables: Dict[str, Any],
    qvariables: Dict[str, Any],
    batch,
    precision: str,
) -> Dict[str, Any]:
    """The manifest's ``calibration`` block: batch identity, activation
    ranges, and the measured quality delta vs the f32 parent."""
    check_precision(precision)
    x = np.asarray(batch)
    if x.ndim < 2 or x.shape[0] == 0:
        raise ValueError(
            f"calibration batch needs shape (n, features...), got {x.shape}"
        )
    f_pred = predict_f32(model, f32_variables, x)
    q_pred = predict_quantized(model, qvariables, x, precision)
    delta = quality_delta(f_pred, q_pred)
    return {
        "batch_size": int(x.shape[0]),
        "batch_shape": list(x.shape),
        "activation_ranges": activation_ranges(model, f32_variables, x),
        "quality_delta_mape": delta["mape"],
        "quality_delta_mae": delta["mae"],
        "max_abs_err": delta["max_abs_err"],
    }


__all__ = [
    "eval_flag",
    "predict_f32",
    "predict_quantized",
    "quality_delta",
    "activation_ranges",
    "calibrate",
]
