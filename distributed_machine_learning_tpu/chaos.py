"""Deterministic fault injection: the harness that proves recovery works.

The seed paper's value proposition is trial-level fault isolation — work
Ray owned there and this framework owns natively (per-trial retry in
``tune/runner.py``, atomic writes in ``tune/storage.py``, replica restart
in ``serve/replica.py``).  None of that machinery is trustworthy until it
has been exercised against real failure shapes: preempted writes,
corrupted checkpoint bytes, flaky shared storage, replicas dying under
traffic.  This module injects exactly those faults, **deterministically**
(seeded, independent of thread timing), at three narrow choke points:

* **storage** — ``FaultyStorage`` wraps any ``StorageBackend``
  (installed process-wide via :func:`activate`, which hooks
  ``tune.storage.get_storage`` INSIDE its retry layer, so injected
  transient errors are absorbed by the same retries real ones are);
* **trial executors** — both executors consult the active plan at each
  report boundary and raise :class:`InjectedTrialCrash`, which follows the
  ordinary error path (retry budget, checkpoint restore, device release);
* **serve** — ``ReplicaSet`` polls the plan per dispatched request and
  hard-kills the scheduled replica, exercising failover, monitor restart,
  and the circuit breaker.

Determinism: probabilistic decisions are a pure hash of
``(seed, op, key, n)`` where ``n`` is a per-``(op, key)`` call counter —
each path's fault sequence is fixed by the seed regardless of how threads
interleave across paths.  Scheduled faults (trial crashes, replica kills)
fire exactly once.  Every injection increments a named counter
(:meth:`FaultPlan.snapshot`), so tests and ``/metrics`` can assert the
faults actually happened.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from distributed_machine_learning_tpu.tune import storage as storage_lib
from distributed_machine_learning_tpu.analysis.locks import named_lock


class InjectedFault(Exception):
    """Base class for every chaos-injected failure (marker for tests)."""


class InjectedIOError(InjectedFault, IOError):
    """Transient storage fault.  Subclasses IOError/OSError so the retry
    policy and existing error handling treat it exactly like the real
    thing."""


class InjectedTrialCrash(InjectedFault, RuntimeError):
    """A trial killed at a scheduled epoch (preemption stand-in)."""


class InjectedProducerCrash(InjectedFault, RuntimeError):
    """The host-input producer thread killed at a scheduled chunk index
    (``data/pipeline.py``).  Raised INSIDE the producer; the prefetch ring
    re-raises it on the consumer (trial) thread, so it follows the
    ordinary trial error path — retry budget, checkpoint restore, device
    release — like every other injected crash."""


class InjectedCommitKill(InjectedFault, RuntimeError):
    """A process killed between a sharded checkpoint's chunk writes and its
    COMMIT marker.  Deliberately NOT an OSError: the storage retry policy
    must not absorb it — a real SIGKILL doesn't retry either.  The save
    fails with the generation left uncommitted, exercising the ckpt/
    commit protocol (readers skip it; the manager deletes it on start)."""


class InjectedRefFlipKill(InjectedFault, RuntimeError):
    """A process killed between preparing a content-store ref update and
    landing it (``store/core.set_ref``).  NOT an OSError — the storage
    retry policy must not absorb it (a real SIGKILL doesn't retry).  The
    atomic-ref contract means the OLD ref value survives intact; the
    in-flight publish's blobs stay unreferenced until GC collects them."""


class InjectedSwapCrash(InjectedFault, RuntimeError):
    """A hot-swap procedure killed after it has switched SOME slots but
    before the set's bundle pointer moved — the mid-promotion crash.  The
    fleet is left mixed but every slot is serving; the promotion driver
    (``loop/controller.py``) must converge it back to one bundle."""


class InjectedControllerCrash(InjectedFault, RuntimeError):
    """The self-healing loop controller killed right after journaling a
    state (``loop/journal.py``) — the crash-between-durable-states fault.
    A fresh controller must resume from the journal and complete the
    episode exactly once."""


def _hash_fraction(*parts) -> float:
    """Uniform [0, 1) value from a stable hash of the parts."""
    h = hashlib.sha256("/".join(str(p) for p in parts).encode()).digest()
    return int.from_bytes(h[:8], "little") / 2**64


class FaultPlan:
    """A seeded schedule of faults.

    Probabilistic faults (rates in [0, 1], decided per call as described in
    the module docstring):

    * ``write_error_rate`` / ``read_error_rate`` — raise
      :class:`InjectedIOError` before the backend runs (transient: the
      retry's next attempt re-rolls).
    * ``slow_rate`` / ``slow_s`` — sleep ``slow_s`` before the operation
      (a degraded-storage stall; keep it <= 0.2s in CI-tier tests).
    * ``artifact_fetch_error_rate`` — compile-artifact fetches from the
      cluster head (``tune/cluster.py``) fail with :class:`InjectedIOError`
      before the request leaves the worker; the worker MUST fall back to
      compiling locally (counted as ``fetch_fallbacks`` in the ``compile``
      family) and the sweep must still find the same best trial.
    * ``trace_export_error_rate`` — obs-plane exports (trace merges,
      flight-recorder dumps) fail with :class:`InjectedIOError` before
      the write.  The telemetry plane MUST absorb these — counted as
      ``obs.export_failures``, never a failed trial or request — and a
      faulted sweep must find the same best trial as control.

    Scheduled faults (each fires exactly once):

    * ``corrupt_path_substrings`` — the first write whose path contains
      each substring has its payload bit-flipped ON DISK (the manifest
      checksum is computed upstream, so restore detects the damage).
    * ``chunk_write_error_rate`` — like ``write_error_rate`` but ONLY for
      checkpoint chunk payloads: sharded-checkpoint chunk files
      (``*.chunk``, ``ckpt/format.py``) and content-store blob publishes
      (``blobs/<hh>/<sha256>``, ``store/core.py`` — the same bytes under
      the CAS write path).  Per-chunk fault pressure on the format without
      touching metrics or state writes.  Transient (retries re-roll);
      rates high enough to exhaust the retry budget leave the generation
      uncommitted.
    * ``kill_before_commit`` — path substrings; the first write of a
      ``COMMIT`` marker whose generation path contains each substring
      raises :class:`InjectedCommitKill` instead of landing — the
      kill-between-chunks-and-COMMIT preemption (fires once per entry).
    * ``trial_crashes`` — ``(trial_id, training_iteration)`` pairs; the
      executor raises :class:`InjectedTrialCrash` at that report boundary.
    * ``kill_process_at`` — ``(trial_id, training_iteration, process_index)``
      triples; a GANG MEMBER child (``multihost/_gang_child.py``) whose
      gang process index matches hard-exits (``os._exit``) at that report
      boundary — the member-dies-mid-collective fault the gang teardown
      path exists for: its peers are left blocked in their next
      collective, the head reaps the gang and requeues the trial from its
      newest valid checkpoint.  Fires on the trial's FIRST incarnation
      only (gang children are fresh processes, so the requeued gang must
      pass the same boundary unharmed); the plan reaches the child
      through ``DML_CHAOS_PLAN`` in its spawn environment.
    * ``replica_kills`` — ``(request_index, replica_idx)`` pairs; the
      ReplicaSet kills that replica when its dispatch counter reaches the
      index (1-based: ``(10, 0)`` kills replica 0 at the 10th request).
      ``replica_idx=-1`` kills whichever replica is serving that request —
      the deterministic way to fail an in-flight request.
    * ``kill_gang_member_at_request`` — ``(request_index, process_id)``
      pairs; a SERVING gang member (``serve/_gang_member.py``) whose gang
      process id matches hard-exits (``os._exit``) at the start of its
      N-th predict round (1-based in the gang's own dispatch stream) —
      the member-dies-mid-traffic fault the gang teardown/rebuild path
      exists for: its peers wedge in the round's collective, the parent
      reaps the whole gang, the in-flight request redispatches to a
      surviving gang (zero drops), and the monitor rebuilds.  Fires on
      the gang's FIRST incarnation only (same guard as
      ``kill_process_at``: rebuilt members re-activate the plan from the
      spawn env and must pass the same request index unharmed).
    * ``gang_bootstrap_hang`` — ``(process_id, seconds)`` pairs; a serving
      gang member sleeps that long BEFORE joining jax.distributed (fires
      once per entry, first incarnation only) — the straggler-member
      bootstrap fault: its peers' join barrier expires, dumping a flight
      recording that NAMES the absent process id before
      ``BarrierTimeout`` raises.
    * ``hot_swaps`` — request indices; when the ReplicaSet's dispatch
      counter reaches each one it fires ``on_swap_signal`` (the soak
      harness registers a callback that performs the zero-downtime
      bundle swap, ``serve/swap.py``) on a helper thread — the
      deterministic way to land a model promotion MID-soak, keyed to the
      same dispatch counter as the kills.
    * ``mid_swap_crash`` — slot-switch indices (1-based, counted across
      every ``hot_swap`` this process runs); the swap procedure raises
      :class:`InjectedSwapCrash` right after switching that slot, before
      the set's bundle pointer moves — a promotion that dies halfway,
      leaving a mixed fleet that is still serving.
    * ``corrupt_bundle_on_export`` — number of bundle exports whose
      ``params.msgpack`` is bit-flipped ON DISK after the write
      (``serve/export.write_bundle``); the loader's msgpack restore
      detects the damage, so a corrupt candidate can never be promoted.
    * ``blob_corrupt_on_publish`` — number of content-store blob
      publishes whose bytes are bit-flipped ON DISK as they land
      (``store/core.put_blob``): the stored bytes no longer hash to the
      blob's name, which only ``store verify`` (or a checksum-verifying
      read) can catch — the bit-rot-at-publish fault.
    * ``kill_during_ref_flip`` — path substrings; the first content-store
      ref update whose ref path contains each substring raises
      :class:`InjectedRefFlipKill` BEFORE the atomic replace lands (fires
      once per entry) — the old ref value must survive untouched and the
      orphaned publish's blobs become GC food, never a torn ref.
    * ``controller_crash_at`` — loop-journal state names
      (``loop/journal.py``); the self-healing controller raises
      :class:`InjectedControllerCrash` immediately AFTER journaling each
      scheduled state (fires once per entry) — the crash between durable
      states whose recovery contract is "resume completes the episode
      exactly once".
    * ``kill_head_at`` — a decision number N; the HEAD/driver process
      hard-exits (``os._exit``) immediately after the Nth scheduling
      decision lands durably in the experiment journal
      (``tune/journal.py``) and before its effect happens — the
      journaled-but-not-acted crash window ``resume="auto"`` replays
      through.  Fires only on head incarnation 1 (the resumed head
      re-activates the plan from env and must survive the same
      decision), same guard as ``kill_process_at``.
    * ``kill_head_during_journal_write`` — a decision number N; the head
      dies MID-append of the Nth decision record: half the JSON line is
      written and fsync'd, then ``os._exit`` — the torn-tail fault the
      journal parser must treat as "decision never happened".  Same
      first-incarnation guard.

    Drift injection (``drift_inject`` — the serving-plane distribution
    shift): a dict ``{"at_request": N, "feature_shift": s,
    "label_scale": m, "label_shift": b}``.  From the N-th request on
    (1-based in the caller's own stream index), :meth:`maybe_drift`
    returns the shift spec (else None) and the stream harness applies it
    via :func:`apply_drift` — a seeded covariate shift (per-dimension
    offsets derived from the plan seed) plus an affine label shift, so
    drift e2e tests and the bench section need no real-world data.  The
    first activation counts ``drift_injections``; decisions are pure in
    ``(seed, index)`` (dmlint DML003: no wall-time, no entropy).

    Fail-SLOW faults (each fires exactly once; nothing raises — recovery
    depends on the liveness layer noticing the silence):

    * ``hang_dispatch_at`` — ``(trial_id, training_iteration)`` pairs; the
      executor's report path sleeps ``hang_s`` seconds at that boundary
      (a wedged device dispatch stand-in).  Keep ``hang_s`` small in CI
      (the watchdog deadline under test must be smaller still).
    * ``stall_storage_paths`` / ``stall_storage_ms`` — the first storage
      op whose path contains each substring sleeps ``stall_storage_ms``
      (degraded shared storage that stalls instead of erroring).
    * ``partition_worker`` — ``(result_index, worker_idx, duration_s)``
      triples; when the cluster driver has processed ``result_index``
      result frames, worker ``worker_idx`` is partitioned for
      ``duration_s``: its frames (both directions) are delayed until the
      partition heals — TCP semantics, delivery delayed not dropped — so
      the head's lease expiry, requeue, and self-fencing all exercise.

    Streaming-input faults (``data/pipeline.py``'s prefetch ring):

    * ``slow_producer_ms`` — the producer thread sleeps this long before
      staging EVERY chunk (degraded host input: slow storage, a
      CPU-starved gather).  Training must stay correct with overlap
      efficiency degraded — the counters, not the params, absorb the
      slowdown.  ``slow_producer_match`` — optional ring-name substrings
      (rings are named ``stream-<trial_id>``): only matching producers
      sleep, so ONE trial of a sweep becomes a straggler its peers are
      measured against (the perf anomaly plane must then NAME it —
      ``perf_straggler[<trial_id>]``, perf/anomaly.py).
    * ``producer_crash_at`` — chunk index (0-based, across the trial's
      whole chunk stream); the producer raises
      :class:`InjectedProducerCrash` before staging that chunk.  Fires
      once — the retried incarnation's producer passes the same index.
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        write_error_rate: float = 0.0,
        read_error_rate: float = 0.0,
        slow_rate: float = 0.0,
        slow_s: float = 0.02,
        artifact_fetch_error_rate: float = 0.0,
        trace_export_error_rate: float = 0.0,
        chunk_write_error_rate: float = 0.0,
        kill_before_commit: Sequence[str] = (),
        corrupt_path_substrings: Sequence[str] = (),
        trial_crashes: Iterable[Tuple[str, int]] = (),
        kill_process_at: Iterable[Tuple[str, int, int]] = (),
        replica_kills: Iterable[Tuple[int, int]] = (),
        kill_gang_member_at_request: Iterable[Tuple[int, int]] = (),
        gang_bootstrap_hang: Iterable[Tuple[int, float]] = (),
        hot_swaps: Iterable[int] = (),
        mid_swap_crash: Iterable[int] = (),
        corrupt_bundle_on_export: int = 0,
        blob_corrupt_on_publish: int = 0,
        kill_during_ref_flip: Sequence[str] = (),
        controller_crash_at: Sequence[str] = (),
        kill_head_at: Optional[int] = None,
        kill_head_during_journal_write: Optional[int] = None,
        drift_inject: Optional[Dict[str, float]] = None,
        hang_dispatch_at: Iterable[Tuple[str, int]] = (),
        hang_s: float = 1.5,
        stall_storage_paths: Sequence[str] = (),
        stall_storage_ms: float = 0.0,
        partition_worker: Iterable[Tuple[int, int, float]] = (),
        slow_producer_ms: float = 0.0,
        slow_producer_match: Sequence[str] = (),
        producer_crash_at: Optional[int] = None,
    ):
        self.seed = seed
        self.write_error_rate = float(write_error_rate)
        self.read_error_rate = float(read_error_rate)
        self.slow_rate = float(slow_rate)
        self.slow_s = float(slow_s)
        self.artifact_fetch_error_rate = float(artifact_fetch_error_rate)
        self.trace_export_error_rate = float(trace_export_error_rate)
        self.chunk_write_error_rate = float(chunk_write_error_rate)
        self._commit_kill_pending: List[str] = list(kill_before_commit)
        self._corrupt_pending: List[str] = list(corrupt_path_substrings)
        self._trial_crashes = {(str(t), int(i)) for t, i in trial_crashes}
        self._process_kills = {
            (str(t), int(i), int(p)) for t, i, p in kill_process_at
        }
        self._kills = sorted(
            ((int(n), int(r)) for n, r in replica_kills), reverse=True
        )
        self._gang_member_kills = {
            (int(n), int(p)) for n, p in kill_gang_member_at_request
        }
        self._gang_bootstrap_hangs = {
            int(p): float(s) for p, s in gang_bootstrap_hang
        }
        self._hot_swaps = sorted((int(n) for n in hot_swaps), reverse=True)
        self._mid_swap_crashes = sorted(
            (int(n) for n in mid_swap_crash), reverse=True
        )
        self._bundle_corruptions_pending = int(corrupt_bundle_on_export)
        self._blob_corruptions_pending = int(blob_corrupt_on_publish)
        self._ref_flip_kill_pending: List[str] = list(kill_during_ref_flip)
        self._controller_crashes: List[str] = [
            str(s) for s in controller_crash_at
        ]
        self._kill_head_at = (
            int(kill_head_at) if kill_head_at is not None else None
        )
        self._torn_journal_at = (
            int(kill_head_during_journal_write)
            if kill_head_during_journal_write is not None else None
        )
        self._drift_inject = dict(drift_inject) if drift_inject else None
        self._drift_fired = False
        # Fail-slow faults (PR 3): dispatch hangs, storage stalls, worker
        # partitions — silence, not errors, so only liveness machinery
        # (liveness.py watchdogs, cluster lease expiry) can recover them.
        self._hangs = {(str(t), int(i)) for t, i in hang_dispatch_at}
        self.hang_s = float(hang_s)
        self._stall_pending: List[str] = list(stall_storage_paths)
        self.stall_storage_ms = float(stall_storage_ms)
        self._partitions = sorted(
            ((int(n), int(w), float(d)) for n, w, d in partition_worker),
            reverse=True,
        )
        self.slow_producer_ms = float(slow_producer_ms)
        self.slow_producer_match = tuple(
            str(s) for s in slow_producer_match
        )
        self._producer_crash_at = (
            int(producer_crash_at) if producer_crash_at is not None else None
        )
        self._lock = named_lock("chaos.plan")
        self._op_counts: Dict[Tuple[str, str], int] = {}
        self._counters: Dict[str, int] = {}
        self._submit_count = 0
        self._result_count = 0
        self._swap_slot_count = 0
        self.corrupted_paths: List[str] = []

    # -- bookkeeping ---------------------------------------------------------

    def _next_index(self, op: str, key: str) -> int:
        with self._lock:
            n = self._op_counts.get((op, key), 0)
            self._op_counts[(op, key)] = n + 1
            return n

    def _count(self, name: str) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + 1

    def snapshot(self) -> Dict[str, int]:
        """Copy of the injected-fault counters (what actually fired)."""
        with self._lock:
            return dict(self._counters)

    # -- storage faults ------------------------------------------------------

    def _roll(self, op: str, key: str, rate: float) -> bool:
        if rate <= 0.0:
            return False
        n = self._next_index(op, key)
        return _hash_fraction(self.seed, op, key, n) < rate

    def on_storage_op(self, op: str, path: str) -> None:
        """Called by FaultyStorage before the real backend op; may sleep
        and/or raise InjectedIOError."""
        if self.stall_storage_ms > 0:
            with self._lock:
                hit = next(
                    (s for s in self._stall_pending if s in path), None
                )
                if hit is not None:
                    self._stall_pending.remove(hit)
                    self._counters["storage_stalls"] = (
                        self._counters.get("storage_stalls", 0) + 1
                    )
            if hit is not None:
                time.sleep(self.stall_storage_ms / 1000.0)
        if self._roll("slow", f"{op}:{path}", self.slow_rate):
            self._count("storage_slow")
            time.sleep(self.slow_s)
        if op == "write" and path.rstrip("/").endswith("/COMMIT"):
            # Kill-between-chunks-and-COMMIT: the generation's data is all
            # on storage, its marker never lands — a preempted save.
            with self._lock:
                hit = next(
                    (s for s in self._commit_kill_pending if s in path), None
                )
                if hit is not None:
                    self._commit_kill_pending.remove(hit)
                    self._counters["commit_kills"] = (
                        self._counters.get("commit_kills", 0) + 1
                    )
            if hit is not None:
                raise InjectedCommitKill(
                    f"injected kill before COMMIT of {path}"
                )
        if (
            op == "write"
            and (path.endswith(".chunk") or "/blobs/" in path)
            and self._roll("chunk_write", path, self.chunk_write_error_rate)
        ):
            self._count("chunk_write_errors")
            raise InjectedIOError(
                f"injected transient chunk write fault on {path}"
            )
        rate = (self.write_error_rate if op == "write"
                else self.read_error_rate if op == "read" else 0.0)
        if self._roll(op, path, rate):
            self._count(f"storage_{op}_errors")
            raise InjectedIOError(
                f"injected transient {op} fault on {path}"
            )

    def corrupt_write(self, path: str, data: bytes) -> bytes:
        """Return ``data``, bit-flipped once per scheduled path substring."""
        with self._lock:
            hit = next(
                (s for s in self._corrupt_pending if s in path), None
            )
            if hit is None:
                return data
            self._corrupt_pending.remove(hit)
            self.corrupted_paths.append(path)
            self._counters["storage_corruptions"] = (
                self._counters.get("storage_corruptions", 0) + 1
            )
        return corrupt_bytes(data)

    def on_artifact_fetch(self, key: str) -> None:
        """Called by a cluster worker before asking the head for compile
        artifacts under ``key``; may raise :class:`InjectedIOError` (the
        worker's fallback is a local compile, never a failed trial)."""
        if self._roll("artifact_fetch", key, self.artifact_fetch_error_rate):
            self._count("artifact_fetch_errors")
            raise InjectedIOError(
                f"injected artifact fetch fault for {key}"
            )

    def on_trace_export(self, path: str) -> None:
        """Called by the obs plane before a trace export / flight dump
        write; may raise :class:`InjectedIOError`.  The decision key is
        the path with volatile per-run digits stripped, so a sweep's Nth
        export faults identically regardless of pids/sequence numbers."""
        import re as _re

        key = _re.sub(r"\d+", "#", path.rsplit("/", 1)[-1])
        if self._roll("trace_export", key, self.trace_export_error_rate):
            self._count("trace_export_errors")
            raise InjectedIOError(
                f"injected trace export fault for {path}"
            )

    # -- trial faults --------------------------------------------------------

    def maybe_crash_trial(self, trial_id: str, iteration: int) -> None:
        """Raise InjectedTrialCrash if (trial_id, iteration) is scheduled.
        Fires once — the retried incarnation passes the same boundary."""
        key = (str(trial_id), int(iteration))
        with self._lock:
            if key not in self._trial_crashes:
                return
            self._trial_crashes.discard(key)
            self._counters["trial_crashes"] = (
                self._counters.get("trial_crashes", 0) + 1
            )
        raise InjectedTrialCrash(
            f"injected crash: {trial_id} at iteration {iteration}"
        )

    def maybe_kill_process(
        self, trial_id: str, iteration: int, process_index: int,
        incarnation: int = 1,
    ) -> None:
        """Hard-exit THIS process if (trial_id, iteration, process_index)
        is scheduled — a gang member dying mid-collective.  ``os._exit``
        (no unwinding, no frames flushed): a preempted host doesn't run
        finally-blocks either.  Fires only on the trial's FIRST
        incarnation: gang children are fresh processes re-activating the
        plan from the spawn env, so the usual in-process fires-once
        bookkeeping cannot span a retry — the incarnation guard is what
        lets the requeued gang pass the same boundary and finish.  The
        counter increment is best-effort forensics for same-process
        observers; cross-process assertions read the head's
        gang_teardown/requeue counters instead."""
        if int(incarnation) > 1:
            return
        key = (str(trial_id), int(iteration), int(process_index))
        with self._lock:
            if key not in self._process_kills:
                return
            self._process_kills.discard(key)
            self._counters["process_kills"] = (
                self._counters.get("process_kills", 0) + 1
            )
        import os

        os._exit(86)

    def maybe_hang_dispatch(self, trial_id: str, iteration: int) -> None:
        """Sleep ``hang_s`` if (trial_id, iteration) is scheduled — a
        dispatch that goes silent instead of erroring.  Fires once; the
        recovered/retried incarnation passes the same boundary."""
        key = (str(trial_id), int(iteration))
        with self._lock:
            if key not in self._hangs:
                return
            self._hangs.discard(key)
            self._counters["dispatch_hangs"] = (
                self._counters.get("dispatch_hangs", 0) + 1
            )
        time.sleep(self.hang_s)

    # -- streaming-input faults ----------------------------------------------

    def maybe_producer_fault(
        self, chunk_index: int, name: Optional[str] = None
    ) -> None:
        """Called by the prefetch ring's producer thread before staging
        each chunk: sleeps ``slow_producer_ms`` (every chunk), raises
        :class:`InjectedProducerCrash` at the scheduled index (once).

        With ``slow_producer_match`` set, only rings whose ``name``
        contains one of the substrings sleep (the ring is named
        ``stream-<trial_id>``) — the straggler fault: ONE trial of a
        sweep degrades while its peers run clean, and the perf anomaly
        plane must name it.  Substring matching against the caller-owned
        ring name is deterministic (dmlint DML003: no entropy, no
        wall-time in the decision)."""
        if self.slow_producer_ms > 0 and (
            not self.slow_producer_match
            or (name is not None and any(
                s in name for s in self.slow_producer_match
            ))
        ):
            self._count("producer_slowdowns")
            time.sleep(self.slow_producer_ms / 1000.0)
        crash = False
        with self._lock:
            if self._producer_crash_at is not None \
                    and int(chunk_index) >= self._producer_crash_at:
                self._producer_crash_at = None
                self._counters["producer_crashes"] = (
                    self._counters.get("producer_crashes", 0) + 1
                )
                crash = True
        if crash:
            raise InjectedProducerCrash(
                f"injected producer crash at chunk {chunk_index}"
            )

    # -- cluster faults ------------------------------------------------------

    def poll_worker_partition(self) -> Optional[Tuple[int, float]]:
        """Advance the driver's result counter; return
        ``(worker_idx, duration_s)`` when a scheduled partition comes due
        (else None).  Called by the cluster driver once per processed
        result frame — deterministic in the frame stream, not wall time."""
        with self._lock:
            self._result_count += 1
            if (
                self._partitions
                and self._result_count >= self._partitions[-1][0]
            ):
                _, idx, duration = self._partitions.pop()
                self._counters["worker_partitions"] = (
                    self._counters.get("worker_partitions", 0) + 1
                )
                return idx, duration
        return None

    # -- serve faults --------------------------------------------------------

    def poll_replica_kill(self) -> Optional[int]:
        """Advance the dispatch counter; return a replica index to kill when
        a scheduled kill comes due (else None)."""
        with self._lock:
            self._submit_count += 1
            if self._kills and self._submit_count >= self._kills[-1][0]:
                _, idx = self._kills.pop()
                self._counters["replica_kills"] = (
                    self._counters.get("replica_kills", 0) + 1
                )
                return idx
        return None

    def maybe_kill_gang_member(
        self, request_n: int, process_id: int, incarnation: int = 1,
    ) -> None:
        """Hard-exit THIS serving gang member if ``(request_n,
        process_id)`` is scheduled — called by ``serve/_gang_member.py``
        at the start of every predict round (``request_n`` 1-based in the
        gang's own dispatch stream), BEFORE the round's collective, so the
        surviving peers wedge exactly where a preempted host would leave
        them.  ``os._exit`` (no unwinding).  First incarnation only: the
        rebuilt gang's members re-activate the plan from the spawn env and
        must serve the same request index unharmed (the
        ``maybe_kill_process`` guard).  The counter is best-effort
        forensics for same-process observers; cross-process assertions
        read the parent's gang teardown/rebuild counters."""
        if int(incarnation) > 1:
            return
        key = (int(request_n), int(process_id))
        with self._lock:
            if key not in self._gang_member_kills:
                return
            self._gang_member_kills.discard(key)
            self._counters["gang_member_kills"] = (
                self._counters.get("gang_member_kills", 0) + 1
            )
        import os

        os._exit(86)

    def maybe_gang_bootstrap_hang(
        self, process_id: int, incarnation: int = 1,
    ) -> None:
        """Sleep the scheduled duration if ``process_id`` has a pending
        ``gang_bootstrap_hang`` entry — called by ``serve/_gang_member.py``
        BEFORE ``join_gang``, so the member's peers sit at the all-joined
        barrier until its deadline expires and the flight dump names THIS
        process id absent.  Fires once per entry, first incarnation only
        (the rebuilt member must bootstrap clean)."""
        if int(incarnation) > 1:
            return
        with self._lock:
            seconds = self._gang_bootstrap_hangs.pop(int(process_id), None)
            if seconds is None:
                return
            self._counters["gang_bootstrap_hangs"] = (
                self._counters.get("gang_bootstrap_hangs", 0) + 1
            )
        time.sleep(seconds)

    def poll_hot_swap(self) -> bool:
        """True when a scheduled mid-soak bundle swap comes due.  Reads the
        dispatch counter :meth:`poll_replica_kill` advances (call order in
        ``ReplicaSet.submit``: kill poll first, then this) so kills and
        swaps share one deterministic request timeline."""
        with self._lock:
            if self._hot_swaps and self._submit_count >= self._hot_swaps[-1]:
                self._hot_swaps.pop()
                self._counters["hot_swap_signals"] = (
                    self._counters.get("hot_swap_signals", 0) + 1
                )
                return True
        return False

    def maybe_mid_swap_crash(self) -> None:
        """Called by ``serve/swap.hot_swap`` after EACH slot switch;
        raises :class:`InjectedSwapCrash` when a scheduled slot-switch
        index comes due.  The counter is process-global across swaps, so
        ``mid_swap_crash=(2,)`` kills the promotion after its second slot
        (or the second swap's first slot on 1-replica sets)."""
        with self._lock:
            self._swap_slot_count += 1
            slot = self._swap_slot_count
            due = (
                self._mid_swap_crashes
                and slot >= self._mid_swap_crashes[-1]
            )
            if due:
                self._mid_swap_crashes.pop()
                self._counters["mid_swap_crashes"] = (
                    self._counters.get("mid_swap_crashes", 0) + 1
                )
        if due:
            raise InjectedSwapCrash(
                f"injected crash mid-swap at slot switch {slot}"
            )

    # -- loop faults ---------------------------------------------------------

    def corrupt_bundle_export(self, path: str, data: bytes) -> bytes:
        """Called by ``serve/export.write_bundle`` with the params payload
        it just serialized; returns it bit-flipped while scheduled
        corruptions remain (``corrupt_bundle_on_export``), counting
        ``bundle_corruptions`` and recording the path."""
        with self._lock:
            if self._bundle_corruptions_pending <= 0:
                return data
            self._bundle_corruptions_pending -= 1
            self.corrupted_paths.append(path)
            self._counters["bundle_corruptions"] = (
                self._counters.get("bundle_corruptions", 0) + 1
            )
        return corrupt_bytes(data)

    # -- content-store faults ------------------------------------------------

    def corrupt_blob_publish(self, path: str, data: bytes) -> bytes:
        """Called by ``store/core.put_blob`` with the blob payload about
        to land; returns it bit-flipped while scheduled corruptions
        remain (``blob_corrupt_on_publish``) — the stored bytes then no
        longer hash to the blob's name, which only ``store verify`` (or
        a verifying read) detects.  Counts ``blob_corruptions``."""
        with self._lock:
            if self._blob_corruptions_pending <= 0:
                return data
            self._blob_corruptions_pending -= 1
            self.corrupted_paths.append(path)
            self._counters["blob_corruptions"] = (
                self._counters.get("blob_corruptions", 0) + 1
            )
        return corrupt_bytes(data)

    def maybe_kill_ref_flip(self, path: str) -> None:
        """Raise :class:`InjectedRefFlipKill` before a content-store ref
        update whose path contains a scheduled substring lands (fires
        once per entry; counts ``ref_flip_kills``) — the writer dies mid
        ref flip, the OLD ref value survives."""
        with self._lock:
            hit = next(
                (s for s in self._ref_flip_kill_pending if s in path), None
            )
            if hit is not None:
                self._ref_flip_kill_pending.remove(hit)
                self._counters["ref_flip_kills"] = (
                    self._counters.get("ref_flip_kills", 0) + 1
                )
        if hit is not None:
            raise InjectedRefFlipKill(
                f"injected kill during ref flip of {path}"
            )

    def maybe_crash_controller(self, state: str) -> None:
        """Raise :class:`InjectedControllerCrash` if the loop controller
        just journaled a scheduled ``state`` (fires once per entry) — the
        journal write has already landed, so resume sees this state."""
        with self._lock:
            if state not in self._controller_crashes:
                return
            self._controller_crashes.remove(state)
            self._counters["controller_crashes"] = (
                self._counters.get("controller_crashes", 0) + 1
            )
        raise InjectedControllerCrash(
            f"injected controller crash after journaling {state!r}"
        )

    def maybe_kill_head(self, decision_n: int, incarnation: int = 1) -> None:
        """Hard-exit the HEAD process if the scheduled decision number has
        been reached — called by ``tune/journal.py`` right after a
        decision record lands durably (fsync'd) and BEFORE its effect
        happens, so resume must replay a journaled-but-unacted decision.
        ``os._exit`` (no unwinding): a SIGKILLed head doesn't flush
        either.  Fires only on head incarnation 1 — the resumed head
        re-activates the plan from ``DML_CHAOS_PLAN`` and must pass the
        same decision unharmed (the ``maybe_kill_process`` guard)."""
        # dmlint: disable=unguarded-shared-state deliberate lock-free fast path: a stale read costs one extra lock round-trip at most — the armed/threshold check re-runs under the lock before firing
        if int(incarnation) > 1 or self._kill_head_at is None:
            return
        with self._lock:
            if (self._kill_head_at is None
                    or int(decision_n) < self._kill_head_at):
                return
            self._kill_head_at = None
            self._counters["head_kills"] = (
                self._counters.get("head_kills", 0) + 1
            )
        import os

        os._exit(86)

    def poll_torn_journal_write(
        self, decision_n: int, incarnation: int = 1
    ) -> bool:
        """True when the journal should tear THIS decision's append —
        the caller writes half the line, fsyncs, and ``os._exit``s, so
        the journal's tail is a torn record resume must drop.  Fires
        once, first head incarnation only."""
        # dmlint: disable=unguarded-shared-state deliberate lock-free fast path: a stale read costs one extra lock round-trip at most — the armed/threshold check re-runs under the lock before firing
        if int(incarnation) > 1 or self._torn_journal_at is None:
            return False
        with self._lock:
            if (self._torn_journal_at is None
                    or int(decision_n) < self._torn_journal_at):
                return False
            self._torn_journal_at = None
            self._counters["torn_journal_writes"] = (
                self._counters.get("torn_journal_writes", 0) + 1
            )
        return True

    def maybe_drift(self, request_index: int) -> Optional[Dict[str, float]]:
        """The drift-injection decision for the caller's ``request_index``
        (1-based in its own stream): the shift spec once the scheduled
        onset is reached, else None.  Pure in (plan args, index); the
        first activation counts ``drift_injections``."""
        spec = self._drift_inject
        if spec is None or int(request_index) < int(
            spec.get("at_request", 1)
        ):
            return None
        with self._lock:
            if not self._drift_fired:
                self._drift_fired = True
                self._counters["drift_injections"] = (
                    self._counters.get("drift_injections", 0) + 1
                )
        return dict(spec, seed=self.seed)


def apply_drift(spec: Dict[str, float], x, y=None):
    """Apply a :meth:`FaultPlan.maybe_drift` shift spec to one request.

    ``x`` is an ``(rows, ..., features)`` array-like; the covariate shift
    adds ``feature_shift`` scaled by a per-feature-dimension factor in
    [0.75, 1.25) derived from the plan seed — deterministic, and uneven
    across dimensions so a drift detector watching a summary statistic
    cannot be fooled by offsetting shifts.  ``y`` (optional labels) gets
    the affine ``label_scale * y + label_shift``.  Returns ``(x, y)`` as
    numpy arrays (``y`` None if not given)."""
    import numpy as _np

    x = _np.asarray(x, dtype=_np.float32)
    shift = float(spec.get("feature_shift", 0.0))
    if shift:
        dims = x.shape[-1] if x.ndim else 1
        seed = spec.get("seed", 0)
        jitter = _np.asarray(
            [0.75 + 0.5 * _hash_fraction(seed, "drift_dim", d)
             for d in range(dims)],
            dtype=_np.float32,
        )
        x = x + shift * jitter
    if y is not None:
        y = _np.asarray(y, dtype=_np.float32)
        y = y * float(spec.get("label_scale", 1.0)) + float(
            spec.get("label_shift", 0.0)
        )
    return x, y


def corrupt_bytes(data: bytes, flip_every: int = 97) -> bytes:
    """Deterministically damage a payload (bit-flip a stride of bytes) —
    shared by the plan and by tests that corrupt stored files directly."""
    buf = bytearray(data)
    for i in range(0, len(buf), flip_every):
        buf[i] ^= 0xFF
    return bytes(buf)


class FaultyStorage(storage_lib.StorageBackend):
    """Wraps a real backend; consults the plan before every operation."""

    def __init__(self, inner: storage_lib.StorageBackend, plan: FaultPlan):
        self.inner = inner
        self.plan = plan

    def write_bytes(self, path: str, data: bytes) -> str:
        self.plan.on_storage_op("write", path)
        return self.inner.write_bytes(path, self.plan.corrupt_write(path, data))

    def read_bytes(self, path: str) -> Optional[bytes]:
        self.plan.on_storage_op("read", path)
        return self.inner.read_bytes(path)

    def exists(self, path: str) -> bool:
        self.plan.on_storage_op("exists", path)
        return self.inner.exists(path)

    def listdir(self, path: str) -> List[str]:
        self.plan.on_storage_op("listdir", path)
        return self.inner.listdir(path)

    def delete(self, path: str) -> None:
        self.plan.on_storage_op("delete", path)
        return self.inner.delete(path)

    def join(self, *parts: str) -> str:
        return self.inner.join(*parts)


# -- process-wide activation --------------------------------------------------

_active_plan: Optional[FaultPlan] = None


def activate(plan: FaultPlan) -> None:
    """Install ``plan`` process-wide: storage faults via the get_storage
    fault wrapper, trial/serve faults via :func:`active_plan` polling.
    The plan's injected-fault counters also register as the
    ``injected_faults`` family in the unified metrics registry, so a
    chaos run's ``/metrics`` and flight dumps carry what fired."""
    global _active_plan
    _active_plan = plan
    storage_lib.set_fault_wrapper(lambda backend: FaultyStorage(backend, plan))
    from distributed_machine_learning_tpu.obs import get_registry

    get_registry().register_family("injected_faults", plan)


def deactivate() -> None:
    global _active_plan
    plan, _active_plan = _active_plan, None
    storage_lib.set_fault_wrapper(None)
    if plan is not None:
        from distributed_machine_learning_tpu.obs import get_registry

        get_registry().unregister_family("injected_faults", plan)


def active_plan() -> Optional[FaultPlan]:
    return _active_plan


@contextlib.contextmanager
def active(plan: FaultPlan):
    """``with chaos.active(FaultPlan(...)):`` — scoped activation."""
    activate(plan)
    try:
        yield plan
    finally:
        deactivate()


PLAN_ENV_VAR = "DML_CHAOS_PLAN"


def plan_from_env() -> Optional[FaultPlan]:
    """Build a FaultPlan from the ``DML_CHAOS_PLAN`` env var (JSON kwargs
    for :class:`FaultPlan`), or None when unset/unparsable.

    This is how faults reach SUBPROCESSES: ``chaos.activate`` is
    process-local, but cluster worker supervisors and process-executor
    children are separate processes — the chaos harness sets the env var in
    their spawn environment and the worker entrypoint activates the plan at
    startup, so a seeded hang/crash schedule lands on the host that
    actually runs the trial."""
    import json
    import os

    raw = os.environ.get(PLAN_ENV_VAR)
    if not raw:
        return None
    try:
        kwargs = json.loads(raw)
        return FaultPlan(**kwargs)
    except (ValueError, TypeError) as exc:
        print(f"[chaos] ignoring unparsable {PLAN_ENV_VAR}: {exc!r}",
              flush=True)
        return None


def activate_from_env() -> Optional[FaultPlan]:
    """``plan_from_env()`` + ``activate`` in one call (worker entrypoints)."""
    plan = plan_from_env()
    if plan is not None:
        activate(plan)
    return plan
