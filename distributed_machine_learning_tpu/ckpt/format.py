"""Sharded, topology-portable checkpoint format.

A checkpoint *generation* is a directory (any ``tune.storage`` scheme)::

    gen_000007/
        L3.0-0.chunk      raw little-endian bytes of one shard of leaf 3
        L3.4-0.chunk      (name = leaf index + the chunk's global start
        ...                offsets, so names are deterministic across hosts
        index.json         and re-saves)
        COMMIT

``index.json`` maps the pytree back together: a JSON skeleton of the tree
(dicts/lists with ``{"__leaf__": n}`` markers), and per leaf its global
shape, dtype, and the chunk table — each chunk's file name, global
``start``/``stop`` offsets, byte count, and sha256.  Non-array leaves
(ints, strings, lists of strings, ...) are stored literally in the index.

Why per-shard chunks instead of one msgpack blob (``tune/checkpoint.py``'s
legacy format): each host serializes only the shards it actually holds
(no all-gather through one host), and a restore reads only the chunks the
*target* sharding needs — which is what makes a checkpoint saved on one
mesh restorable on a different mesh, a different device count, or a single
host (the Orbax design, PAPERS.md).

Commit protocol (atomicity across many files; single-file writes are
already atomic in ``tune.storage``): chunks first, then ``index.json``,
then a ``COMMIT`` marker carrying the index's sha256 — written LAST.  A
save preempted anywhere leaves a generation without a valid ``COMMIT``,
which every reader treats as nonexistent and the
:class:`~distributed_machine_learning_tpu.ckpt.manager.CheckpointManager`
deletes on start.  No pickle anywhere: raw array bytes + JSON keep the
format process- and framework-portable.

Multi-host note: chunk names derive from global offsets and the index's
chunk table is computed from the sharding's ``devices_indices_map`` (which
every process can evaluate), so hosts write disjoint chunk files into the
same directory and process 0 writes the index/COMMIT.  Chunks written by
other hosts carry ``"sha256": null`` in process 0's index (their bytes
never crossed hosts); they are decode-checked on read instead.

Content-addressed mode (ISSUE 20; single-process saves, default on, see
``store.store_enabled``): chunk PAYLOADS land in the sibling content
store instead of per-generation ``*.chunk`` files.  Each chunk record
additionally carries ``"blobs": [{"h": <sha256>, "nbytes": n}, ...]`` —
row-aligned pieces published via ``ContentStore.put_blob``, so a piece
unchanged between generation N and N+1 (or a PBT donor row shared across
population members) is a dedup hit, not a write.  The index records the
store root under ``"store"`` and a ``ckpt-<hash(path)>`` ref points GC at
the generation's manifest; the commit protocol is unchanged (blobs ->
manifest -> ref -> index.json -> COMMIT), restores stay bit-identical,
and multi-process saves keep the legacy chunk-file layout (other hosts'
chunk hashes never cross hosts, so one process cannot name their blobs).
"""

from __future__ import annotations

import hashlib
import json
import re
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributed_machine_learning_tpu import store as store_lib
from distributed_machine_learning_tpu.ckpt.metrics import get_metrics
from distributed_machine_learning_tpu.tune.storage import get_storage

FORMAT_VERSION = 1
INDEX_NAME = "index.json"
COMMIT_NAME = "COMMIT"
CHUNK_SUFFIX = ".chunk"

GEN_RE = re.compile(r"^gen_(\d+)$")

_LEAF_KEY = "__leaf__"


class CheckpointCorruptionError(Exception):
    """Stored checkpoint bytes fail their checksum or do not decode.

    Canonical definition (``tune.checkpoint`` re-exports it): both formats
    raise the same class so every fallback path catches one thing.
    """


def generation_name(step: int) -> str:
    return f"gen_{int(step):06d}"


def step_of_generation(path: str) -> Optional[int]:
    import posixpath

    m = GEN_RE.match(posixpath.basename(path.rstrip("/")))
    return int(m.group(1)) if m else None


def is_sharded_path(path: str) -> bool:
    """True when ``path`` names a sharded generation directory — by name
    (``gen_NNNNNN``) or by containing an ``index.json``."""
    import posixpath

    base = posixpath.basename(path.rstrip("/"))
    if GEN_RE.match(base):
        return True
    backend, p = get_storage(path)
    return backend.exists(backend.join(p, INDEX_NAME))


# -- dtype portability ---------------------------------------------------------


def _dtype_str(dt) -> str:
    return np.dtype(dt).name


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 & friends live in ml_dtypes (a jax dependency) and may
        # not be registered with bare numpy on every version.
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


# -- host snapshot -------------------------------------------------------------


class HostLeaf:
    """Host-side snapshot of one array leaf: global shape/dtype plus the
    chunks THIS process holds, each a ``(start, stop, ndarray)`` triple in
    global coordinates.  ``remote_chunks`` lists (start, stop) of shards
    owned by other hosts (chunk table entries without local bytes).
    ``partition`` records the leaf's PartitionSpec (JSON-rendered, with
    the mesh axis sizes) when the source array carried a NamedSharding —
    the rule-derived layout rides in the index so a restore can rebuild
    it without re-resolving the rule table."""

    __slots__ = ("shape", "dtype", "chunks", "remote_chunks", "partition")

    def __init__(self, shape, dtype, chunks, remote_chunks=(),
                 partition=None):
        self.shape = tuple(int(d) for d in shape)
        self.dtype = _dtype_str(dtype)
        self.chunks: List[Tuple[Tuple[int, ...], Tuple[int, ...], np.ndarray]] = chunks
        self.remote_chunks: List[Tuple[Tuple[int, ...], Tuple[int, ...]]] = list(
            remote_chunks
        )
        self.partition = partition


def _partition_of(x) -> Optional[Dict[str, Any]]:
    """``{"spec": [...], "mesh": {axis: size}}`` for a NamedSharding-backed
    jax.Array; None otherwise (host arrays, single-device placements)."""
    sharding = getattr(x, "sharding", None)
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None:
        return None
    from distributed_machine_learning_tpu.parallel.partition import (
        mesh_axis_sizes,
        spec_to_jsonable,
    )

    try:
        return {
            "spec": spec_to_jsonable(spec),
            "mesh": mesh_axis_sizes(mesh),
        }
    except Exception:  # noqa: BLE001 - layout metadata is best-effort
        return None


def _norm_index(index, shape) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """A jax shard index (tuple of slices) -> concrete (start, stop)."""
    start, stop = [], []
    for sl, dim in zip(index, shape):
        start.append(int(sl.start) if sl.start is not None else 0)
        stop.append(int(sl.stop) if sl.stop is not None else int(dim))
    return tuple(start), tuple(stop)


def _is_jax_array(x) -> bool:
    try:
        import jax

        return isinstance(x, jax.Array)
    except Exception:  # pragma: no cover - jax always present here
        return False


def snapshot_leaf(x):
    """Array-like -> :class:`HostLeaf` (device->host COPY happens HERE, so
    an async writer that snapshots at submit time is donation-safe);
    anything else is returned as a literal.

    The copies below must be real copies, never views: ``np.asarray`` on a
    CPU-backed ``jax.Array`` aliases the device buffer zero-copy, and a
    donated buffer (``donate_argnums``) is reused in place by later train
    steps — an aliasing snapshot would serialize FUTURE state under a past
    generation's name (observed: an epoch-6 population checkpoint carrying
    epoch-8 optimizer counts)."""
    if _is_jax_array(x):
        shape = tuple(x.shape)
        shards = getattr(x, "addressable_shards", None)
        if shards:
            chunks: Dict[Tuple, Tuple] = {}
            for s in shards:
                start, stop = _norm_index(s.index, shape)
                key = (start, stop)
                # One writer per distinct global slice: replicas beyond
                # replica 0 hold identical bytes.
                if s.replica_id != 0 or key in chunks:
                    continue
                chunks[key] = (start, stop, np.array(s.data, copy=True))
            remote = []
            try:
                import jax

                if jax.process_count() > 1:  # pragma: no cover - multihost
                    seen = set(chunks)
                    for idx in x.sharding.devices_indices_map(shape).values():
                        start, stop = _norm_index(idx, shape)
                        if (start, stop) not in seen:
                            seen.add((start, stop))
                            remote.append((start, stop))
            except Exception:
                remote = []
            return HostLeaf(shape, x.dtype, list(chunks.values()), remote,
                            partition=_partition_of(x))
        arr = np.array(x, copy=True)
        return HostLeaf(
            arr.shape, arr.dtype,
            [(tuple(0 for _ in arr.shape), tuple(arr.shape), arr)],
            partition=_partition_of(x),
        )
    if isinstance(x, (np.ndarray, np.generic)):
        arr = np.asarray(x)
        return HostLeaf(
            arr.shape, arr.dtype,
            [(tuple(0 for _ in arr.shape), tuple(arr.shape), arr.copy())],
        )
    return x


def snapshot_tree(tree) -> Tuple[Any, List[Any]]:
    """Walk ``tree`` into a JSON skeleton plus a leaf list of
    :class:`HostLeaf` / literal values.

    The tree is normalized through flax's ``to_state_dict`` first (tuples
    and lists become index-keyed dicts, custom nodes their state dicts) so
    a sharded restore returns EXACTLY the same container shapes as the
    legacy msgpack restore — every ``restore_into(template, tree)`` call
    site works unchanged whichever format wrote the checkpoint."""
    from flax import serialization

    tree = serialization.to_state_dict(tree)
    leaves: List[Any] = []

    def walk(node):
        if isinstance(node, dict):
            return {str(k): walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)) and not _leaf_like(node):
            return [walk(v) for v in node]
        leaves.append(snapshot_leaf(node))
        return {_LEAF_KEY: len(leaves) - 1}

    def _leaf_like(node) -> bool:
        # Flat lists of scalars/strings (e.g. trial_ids) stay literal
        # leaves; lists containing containers or arrays are structure.
        return all(
            isinstance(v, (str, int, float, bool)) or v is None for v in node
        )

    return walk(tree), leaves


# -- save ----------------------------------------------------------------------


def _chunk_file_name(leaf_idx: int, start: Sequence[int]) -> str:
    offs = "-".join(str(int(s)) for s in start) or "0"
    return f"L{leaf_idx}.{offs}{CHUNK_SUFFIX}"


def _mh_barrier(name: str) -> None:
    """Order a multi-process save's phases (no-op single-process).

    The commit protocol over many WRITERS needs two fences the
    single-process path gets for free from program order: every process's
    stale-COMMIT delete must land before ANY chunk is written (a late
    starter's delete must never remove the marker process 0 just wrote —
    observed in the 2-process probe), and every process's chunks must land
    before process 0 writes the index/COMMIT that names them."""
    import jax

    try:
        nproc = jax.process_count()
    except Exception:  # pragma: no cover - pre-init
        return
    if nproc <= 1:
        return
    from distributed_machine_learning_tpu.multihost.runtime import barrier

    barrier(name)


def _cas_for(path: str) -> Optional["store_lib.ContentStore"]:
    """The content store serving ``path``'s CAS write path — None when
    the store is disabled (``DML_STORE_CKPT=0``) or the save spans
    processes (other hosts' chunk hashes never cross hosts, so one
    process cannot publish a shared blob namespace)."""
    if not store_lib.store_enabled():
        return None
    try:
        import jax

        if jax.process_count() > 1:  # pragma: no cover - multihost
            return None
    except Exception:  # pragma: no cover - pre-init
        pass
    return store_lib.get_store(store_lib.store_root_for(path))


def _row_stride(arr: np.ndarray) -> int:
    """Byte width of one leading-axis row (0 for scalars) — the piece
    boundary that keeps PBT donor rows and unchanged row ranges hashing
    to the same blobs across writers."""
    if arr.ndim < 1:
        return 0
    return int(np.prod(arr.shape[1:], dtype=np.int64)) * arr.dtype.itemsize


def write_snapshot(path: str, skeleton, leaves: List[Any]) -> Tuple[int, int]:
    """Write a snapshotted tree as one generation under ``path``; returns
    ``(bytes_written, chunks_written)``.  Order is the commit protocol:
    chunk payloads -> (CAS mode: manifest -> ref) -> index.json -> COMMIT
    (multi-process: barriers between the phases, see :func:`_mh_barrier`)."""
    backend, p = get_storage(path)
    # Re-saving over a previous attempt at the same step: drop its COMMIT
    # FIRST so no reader ever pairs the old marker with new bytes.
    backend.delete(backend.join(p, COMMIT_NAME))
    _mh_barrier(f"ckpt_clear:{p}")
    cas = _cas_for(p)
    # Pin-then-scan GC contract: every digest is pinned the moment it is
    # published, and the pin is dropped only after the ref (and COMMIT)
    # landed — a concurrent sweep can never collect an in-flight save.
    pin = cas.pin() if cas is not None else None
    gen_digests: List[str] = []
    total_bytes = 0
    total_chunks = 0
    index_leaves: List[Dict[str, Any]] = []
    try:
        for n, leaf in enumerate(leaves):
            if not isinstance(leaf, HostLeaf):
                index_leaves.append({"literal": True, "value": leaf})
                continue
            chunk_recs = []
            for start, stop, arr in leaf.chunks:
                contiguous = np.ascontiguousarray(arr)
                data = contiguous.tobytes()
                fname = _chunk_file_name(n, start)
                rec = {
                    "file": fname,
                    "start": list(start),
                    "stop": list(stop),
                    "nbytes": len(data),
                    "sha256": hashlib.sha256(data).hexdigest(),
                }
                if cas is not None:
                    blob_recs = []
                    for off, ln in store_lib.split_row_aligned(
                        len(data), _row_stride(contiguous)
                    ):
                        digest = cas.put_blob(data[off:off + ln])
                        pin.add(digest)
                        gen_digests.append(digest)
                        blob_recs.append({"h": digest, "nbytes": ln})
                    rec["blobs"] = blob_recs
                else:
                    backend.write_bytes(backend.join(p, fname), data)
                chunk_recs.append(rec)
                total_bytes += len(data)
                total_chunks += 1
            for start, stop in leaf.remote_chunks:  # pragma: no cover - multihost
                chunk_recs.append({
                    "file": _chunk_file_name(n, start),
                    "start": list(start),
                    "stop": list(stop),
                    "nbytes": None,
                    "sha256": None,
                })
            rec = {
                "shape": list(leaf.shape),
                "dtype": leaf.dtype,
                "chunks": chunk_recs,
            }
            if leaf.partition is not None:
                rec["partition"] = leaf.partition
            index_leaves.append(rec)
        # All processes' chunks must be on storage before the index/COMMIT
        # that names them (no-op single-process).
        _mh_barrier(f"ckpt_chunks:{p}")
        try:
            import jax

            process_index = jax.process_index()
        except Exception:  # pragma: no cover - pre-init
            process_index = 0
        if process_index == 0:
            try:
                import jax as _jax

                nproc = _jax.process_count()
            except Exception:  # pragma: no cover - pre-init
                nproc = 1
            index = {
                "format_version": FORMAT_VERSION,
                "tree": skeleton,
                "leaves": index_leaves,
                # Saving-side process layout: consumers (serve/export.py's
                # manifest topology block) can name the training topology
                # without probing chunk files.
                "process_count": nproc,
            }
            if cas is not None:
                # GC root BEFORE visibility: the ref lands ahead of the
                # index/COMMIT so a committed generation is always
                # reachable, while a save that dies here leaves only an
                # unreferenced ref + pinned-then-released blobs — plain
                # GC food, invisible to readers.
                manifest_digest = cas.put_manifest({
                    "kind": "ckpt-generation",
                    "path": p,
                    store_lib.MANIFEST_CHUNKS_KEY: sorted(set(gen_digests)),
                })
                pin.add(manifest_digest)
                cas.set_ref(
                    store_lib.ref_name_for_path("ckpt", p),
                    manifest_digest,
                    meta={"path": p, "kind": "ckpt-generation"},
                )
                index["store"] = {"root": cas.root, "version": 1}
            index_bytes = json.dumps(index, sort_keys=True).encode()
            backend.write_bytes(backend.join(p, INDEX_NAME), index_bytes)
            total_bytes += len(index_bytes)
            commit = {
                "index_sha256": hashlib.sha256(index_bytes).hexdigest(),
                "chunks": total_chunks,
                "bytes": total_bytes,
            }
            backend.write_bytes(
                backend.join(p, COMMIT_NAME), json.dumps(commit).encode()
            )
    finally:
        if pin is not None:
            pin.release()
    return total_bytes, total_chunks


def save_sharded(path: str, tree) -> str:
    """Snapshot + write ``tree`` as a committed generation at ``path``."""
    t0 = time.time()
    skeleton, leaves = snapshot_tree(tree)
    nbytes, nchunks = write_snapshot(path, skeleton, leaves)
    get_metrics().record_save(time.time() - t0, nbytes, max(nchunks, 1))
    return path


# -- read ----------------------------------------------------------------------


def read_index(path: str, verify: bool = True) -> Optional[Dict[str, Any]]:
    """The parsed index of a COMMITTED generation; None when nothing is
    there at all; :class:`CheckpointCorruptionError` for a torn or damaged
    one (missing/invalid COMMIT, checksum mismatch, undecodable JSON)."""
    backend, p = get_storage(path)
    index_raw = backend.read_bytes(backend.join(p, INDEX_NAME))
    commit_raw = backend.read_bytes(backend.join(p, COMMIT_NAME))
    if index_raw is None and commit_raw is None:
        return None
    if commit_raw is None:
        raise CheckpointCorruptionError(
            f"uncommitted generation at {path} (no {COMMIT_NAME} marker — "
            f"the save never finished)"
        )
    if index_raw is None:
        raise CheckpointCorruptionError(
            f"generation at {path} has a {COMMIT_NAME} but no {INDEX_NAME}"
        )
    if verify:
        try:
            expected = json.loads(commit_raw).get("index_sha256")
        except ValueError as exc:
            raise CheckpointCorruptionError(
                f"undecodable {COMMIT_NAME} at {path}: {exc!r}"
            ) from exc
        if expected != hashlib.sha256(index_raw).hexdigest():
            raise CheckpointCorruptionError(
                f"index checksum mismatch at {path}"
            )
    try:
        return json.loads(index_raw)
    except ValueError as exc:
        raise CheckpointCorruptionError(
            f"undecodable {INDEX_NAME} at {path}: {exc!r}"
        ) from exc


def is_committed(path: str) -> bool:
    try:
        return read_index(path) is not None
    except CheckpointCorruptionError:
        return False


class _ChunkReader:
    """Lazy, cached, checksum-verifying chunk access for one generation —
    a restore touches only the chunk payloads its target sharding needs
    (``*.chunk`` files, or the content-store blobs a CAS-mode chunk
    record names — never both)."""

    def __init__(self, path: str, verify: bool = True,
                 store_root: Optional[str] = None):
        self.backend, self.base = get_storage(path)
        self.verify = verify
        self._cache: Dict[str, np.ndarray] = {}
        self.bytes_read = 0
        self._store = (
            store_lib.get_store(store_root) if store_root else None
        )

    def _chunk_bytes(self, rec: Dict[str, Any], fname: str) -> bytes:
        blobs = rec.get("blobs")
        if blobs:
            if self._store is None:
                raise CheckpointCorruptionError(
                    f"chunk {fname} under {self.base} is stored as content "
                    f"blobs but the index names no store root"
                )
            pieces: List[bytes] = []
            for b in blobs:
                piece = self._store.get_blob(b["h"])
                if piece is None:
                    raise CheckpointCorruptionError(
                        f"missing blob {b['h'][:12]}... for chunk {fname} "
                        f"under {self.base} (store {self._store.root})"
                    )
                pieces.append(piece)
            return b"".join(pieces)
        data = self.backend.read_bytes(self.backend.join(self.base, fname))
        if data is None:
            raise CheckpointCorruptionError(
                f"missing chunk {fname} under {self.base}"
            )
        return data

    def chunk_array(self, rec: Dict[str, Any], dtype, shape) -> np.ndarray:
        fname = rec["file"]
        arr = self._cache.get(fname)
        if arr is not None:
            return arr
        data = self._chunk_bytes(rec, fname)
        self.bytes_read += len(data)
        if self.verify and rec.get("sha256") is not None:
            if hashlib.sha256(data).hexdigest() != rec["sha256"]:
                raise CheckpointCorruptionError(
                    f"chunk checksum mismatch: {fname} under {self.base}"
                )
        cshape = tuple(
            int(b) - int(a) for a, b in zip(rec["start"], rec["stop"])
        )
        expected = int(np.prod(cshape, dtype=np.int64)) * dtype.itemsize
        if len(data) != expected:
            raise CheckpointCorruptionError(
                f"chunk {fname} has {len(data)} bytes, expected {expected}"
            )
        arr = np.frombuffer(data, dtype=dtype).reshape(cshape)
        self._cache[fname] = arr
        return arr


def _assemble(
    leaf_rec: Dict[str, Any],
    reader: _ChunkReader,
    requested: Optional[Tuple[slice, ...]] = None,
) -> np.ndarray:
    """Materialize the ``requested`` global slice of one leaf (the whole
    array when None) from the chunks that intersect it."""
    shape = tuple(int(d) for d in leaf_rec["shape"])
    dtype = _np_dtype(leaf_rec["dtype"])
    if requested is None:
        req_start = tuple(0 for _ in shape)
        req_stop = shape
    else:
        req_start, req_stop = _norm_index(requested, shape)
    out_shape = tuple(b - a for a, b in zip(req_start, req_stop))
    out = np.empty(out_shape, dtype=dtype)
    filled = 0
    for rec in leaf_rec["chunks"]:
        c_start = tuple(int(v) for v in rec["start"])
        c_stop = tuple(int(v) for v in rec["stop"])
        i_start = tuple(max(a, b) for a, b in zip(req_start, c_start))
        i_stop = tuple(min(a, b) for a, b in zip(req_stop, c_stop))
        if any(a >= b for a, b in zip(i_start, i_stop)):
            continue  # disjoint: this chunk is never read
        chunk = reader.chunk_array(rec, dtype, shape)
        out_sl = tuple(
            slice(a - r, b - r) for a, b, r in zip(i_start, i_stop, req_start)
        )
        in_sl = tuple(
            slice(a - c, b - c) for a, b, c in zip(i_start, i_stop, c_start)
        )
        out[out_sl] = chunk[in_sl]
        filled += int(np.prod(
            [b - a for a, b in zip(i_start, i_stop)], dtype=np.int64
        ))
    want = int(np.prod(out_shape, dtype=np.int64))
    if filled < want:
        raise CheckpointCorruptionError(
            f"chunk table does not cover the requested region "
            f"({filled}/{want} elements) for a leaf of shape {shape}"
        )
    return out


def _sharding_for(shardings, path_parts: Tuple[str, ...]):
    """Resolve the target sharding for one leaf: ``shardings`` is None, a
    callable ``('a','b','c') -> sharding|None``, or a nested pytree walked
    by the same keys as the checkpointed tree (missing entries -> None =
    plain numpy)."""
    if shardings is None:
        return None
    if callable(shardings):
        return shardings(path_parts)
    node = shardings
    for part in path_parts:
        if isinstance(node, dict):
            node = node.get(part)
        elif isinstance(node, (list, tuple)):
            try:
                node = node[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            break
        if node is None:
            return None
    if isinstance(node, (dict, list, tuple)):
        return None
    return node


def load_sharded(
    path: str,
    verify: bool = True,
    shardings=None,
) -> Optional[Dict[str, Any]]:
    """Restore a generation.  Without ``shardings`` every array leaf is
    gathered to a full numpy array (the single-host/export path).  With
    ``shardings`` (see :func:`_sharding_for`) each array leaf becomes a
    ``jax.Array`` laid out for the TARGET mesh, built with
    ``jax.make_array_from_callback`` so only the chunks intersecting each
    local shard are ever read — the resharding-on-restore path.

    Returns None when nothing exists at ``path``; raises
    :class:`CheckpointCorruptionError` on torn/uncommitted/damaged data.
    """
    t0 = time.time()
    index = read_index(path, verify=verify)
    if index is None:
        return None
    reader = _ChunkReader(
        path, verify=verify,
        store_root=(index.get("store") or {}).get("root"),
    )
    leaves = index["leaves"]

    def rebuild(node, parts: Tuple[str, ...]):
        if isinstance(node, dict) and set(node) == {_LEAF_KEY}:
            rec = leaves[int(node[_LEAF_KEY])]
            if rec.get("literal"):
                return rec.get("value")
            sharding = _sharding_for(shardings, parts)
            if sharding is None:
                return _assemble(rec, reader)
            import jax

            shape = tuple(int(d) for d in rec["shape"])
            return jax.make_array_from_callback(
                shape, sharding, lambda idx, r=rec: _assemble(r, reader, idx)
            )
        if isinstance(node, dict):
            return {k: rebuild(v, parts + (k,)) for k, v in node.items()}
        if isinstance(node, list):
            return [
                rebuild(v, parts + (str(i),)) for i, v in enumerate(node)
            ]
        return node

    tree = rebuild(index["tree"], ())
    get_metrics().record_restore(time.time() - t0, reader.bytes_read)
    return tree


def list_files(path: str) -> List[str]:
    """Names of every file belonging to a generation (for deletion)."""
    backend, p = get_storage(path)
    return backend.listdir(p)


def delete_generation(path: str) -> int:
    """Remove a generation directory and everything in it (COMMIT first, so
    a reader racing the delete sees 'uncommitted', never 'torn'), then its
    content-store ref — a deleted generation whose ref lingered would
    retain its blobs forever (the ``gc_retained`` ref-leak runbook
    signal).  Returns the number of files removed."""
    backend, p = get_storage(path)
    recorded_root = None
    index_raw = backend.read_bytes(backend.join(p, INDEX_NAME))
    if index_raw is not None:
        try:
            recorded_root = (
                json.loads(index_raw).get("store") or {}
            ).get("root")
        except ValueError:
            recorded_root = None
    names = backend.listdir(p)
    ordered = sorted(names, key=lambda n: (n != COMMIT_NAME, n))
    removed = 0
    for name in ordered:
        backend.delete(backend.join(p, name))
        removed += 1
    import os

    if os.path.isdir(p):  # local scheme: clear the now-empty directory
        try:
            os.rmdir(p)
        except OSError:
            pass
    _drop_store_ref(p, recorded_root)
    return removed


def _drop_store_ref(path: str, recorded_root: Optional[str]) -> None:
    """Best-effort: delete the ``ckpt-*`` ref a generation at ``path``
    registered.  Tries the root its index recorded, then the default root
    for the path (a pre-index failure can leave a ref with no index)."""
    roots: List[str] = []
    if recorded_root:
        roots.append(recorded_root)
    try:
        fallback = store_lib.store_root_for(path)
        if fallback not in roots:
            roots.append(fallback)
    except Exception:  # noqa: BLE001 - ref cleanup must never fail a delete
        pass
    name = store_lib.ref_name_for_path("ckpt", path)
    for root in roots:
        try:
            cas = store_lib.get_store(root)
            if cas.read_ref(name) is not None:
                cas.delete_ref(name)
        except Exception:  # noqa: BLE001 - ref cleanup must never fail a delete
            continue


class _NotRefCopyable(Exception):
    """Internal: the source generation has chunk payloads outside the
    content store (legacy layout / multihost save)."""


def ref_copy_subtree(
    src_path: str,
    dst_path: str,
    keys: Sequence[str] = ("params", "batch_stats"),
) -> Optional[Dict[str, Any]]:
    """Publish a COMMITTED generation at ``dst_path`` whose chunk table
    names the SAME content-store blobs as ``src_path``'s sub-tree under
    ``keys`` — a metadata-only export: zero chunk payload bytes move,
    only a new manifest, ref, index and COMMIT.

    Returns ``{"chunks", "bytes_logical", "store_root", "path"}`` on
    success; None when the source cannot be ref-copied (legacy chunk-file
    layout, no store record, or no ``params`` sub-tree) — callers fall
    back to the load-and-reserialize path.  Raises
    :class:`CheckpointCorruptionError` when the source is torn or its
    blobs are missing (a ref-copy must never publish dangling digests).

    The destination registers its OWN ref in the SOURCE's store, so
    pruning the source generation later cannot strand the export: GC
    walks the destination's manifest and retains every shared blob.
    """
    index = read_index(src_path)
    if index is None:
        return None
    root = (index.get("store") or {}).get("root")
    if not root:
        return None
    tree = index.get("tree")
    if not isinstance(tree, dict):
        return None
    sub = {k: tree[k] for k in keys if k in tree}
    if "params" not in sub:
        return None
    src_leaves = index["leaves"]
    new_leaves: List[Dict[str, Any]] = []
    digests: List[str] = []
    bytes_logical = 0
    nchunks = 0

    def renumber(node):
        nonlocal bytes_logical, nchunks
        if isinstance(node, dict) and set(node) == {_LEAF_KEY}:
            rec = src_leaves[int(node[_LEAF_KEY])]
            if not rec.get("literal"):
                for chunk in rec["chunks"]:
                    blobs = chunk.get("blobs")
                    if not blobs:
                        raise _NotRefCopyable()
                    digests.extend(b["h"] for b in blobs)
                    bytes_logical += int(chunk.get("nbytes") or 0)
                    nchunks += 1
            new_leaves.append(rec)
            return {_LEAF_KEY: len(new_leaves) - 1}
        if isinstance(node, dict):
            return {k: renumber(v) for k, v in node.items()}
        if isinstance(node, list):
            return [renumber(v) for v in node]
        return node

    try:
        new_tree = renumber(sub)
    except _NotRefCopyable:
        return None

    cas = store_lib.get_store(root)
    unique = sorted(set(digests))
    missing = [d for d in unique if not cas.has_blob(d)]
    if missing:
        raise CheckpointCorruptionError(
            f"ref-copy source {src_path} names {len(missing)} missing "
            f"blob(s) under {root} (first: {missing[0][:12]}...)"
        )
    backend, dst = get_storage(dst_path)
    backend.delete(backend.join(dst, COMMIT_NAME))
    with cas.pin() as pin:
        for d in unique:
            pin.add(d)
        manifest_digest = cas.put_manifest({
            "kind": "ckpt-refcopy",
            "path": dst,
            "source": get_storage(src_path)[1],
            store_lib.MANIFEST_CHUNKS_KEY: unique,
        })
        pin.add(manifest_digest)
        cas.set_ref(
            store_lib.ref_name_for_path("ckpt", dst),
            manifest_digest,
            meta={"path": dst, "kind": "ckpt-refcopy"},
        )
        new_index = {
            "format_version": FORMAT_VERSION,
            "tree": new_tree,
            "leaves": new_leaves,
            "process_count": 1,
            "store": {"root": root, "version": 1},
        }
        index_bytes = json.dumps(new_index, sort_keys=True).encode()
        backend.write_bytes(backend.join(dst, INDEX_NAME), index_bytes)
        commit = {
            "index_sha256": hashlib.sha256(index_bytes).hexdigest(),
            "chunks": nchunks,
            "bytes": bytes_logical + len(index_bytes),
        }
        backend.write_bytes(
            backend.join(dst, COMMIT_NAME), json.dumps(commit).encode()
        )
    store_lib.get_metrics().add("ref_copies", nchunks)
    return {
        "chunks": nchunks,
        "bytes_logical": bytes_logical,
        "store_root": root,
        "path": dst,
    }


def saved_partition_specs(path: str) -> Optional[Dict[str, Any]]:
    """The rule-derived layout a generation was SAVED under: a pytree (same
    skeleton as the checkpoint) of ``jax.sharding.PartitionSpec`` for every
    leaf that recorded one (None for host/replicated leaves), plus the
    saving mesh's axis sizes under the ``"__mesh__"`` key of the returned
    dict.  Returns None for uncommitted/absent generations.

    This is what lets a restore re-derive NamedShardings on a NEW mesh
    from the same specs (``load_sharded(shardings=...)``) without
    re-resolving the rule table that produced them."""
    index = read_index(path)
    if index is None:
        return None
    from distributed_machine_learning_tpu.parallel.partition import (
        spec_from_jsonable,
    )

    leaves = index["leaves"]
    mesh_axes: Dict[str, int] = {}

    def rebuild(node):
        if isinstance(node, dict) and set(node) == {_LEAF_KEY}:
            rec = leaves[int(node[_LEAF_KEY])]
            part = rec.get("partition")
            if not part:
                return None
            for k, v in (part.get("mesh") or {}).items():
                mesh_axes.setdefault(str(k), int(v))
            return spec_from_jsonable(part.get("spec"))
        if isinstance(node, dict):
            return {k: rebuild(v) for k, v in node.items()}
        if isinstance(node, list):
            return [rebuild(v) for v in node]
        return None

    tree = rebuild(index["tree"])
    return {"specs": tree, "__mesh__": mesh_axes}
