"""Async checkpoint writer: training overlaps checkpoint I/O.

Orbax-style split (PAPERS.md): ``save()`` does only the device->host
snapshot on the calling thread — per-shard, so a sharded array is never
gathered — and returns; serialization, hashing, chunk writes, and the
COMMIT marker all run on one background thread in submission order.  The
caller's next training step runs concurrently with the write.

Error contract: a failed write surfaces on the NEXT ``save()`` (and on
``wait_until_finished()``) as the original exception — a sweep that keeps
checkpointing into a dead filesystem fails at the next save boundary
instead of silently training past its last durable state.

Overlap accounting is counter-based (``ckpt.metrics``): submit records the
global step counter; completion credits the steps that elapsed while the
write was in flight.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional, Tuple

from distributed_machine_learning_tpu.ckpt import format as fmt
from distributed_machine_learning_tpu.ckpt.metrics import get_metrics
from distributed_machine_learning_tpu.analysis.locks import named_lock


class AsyncCheckpointer:
    """One background writer; submission order is write order."""

    def __init__(self, log: Optional[Callable[[str], None]] = None):
        self._q: "queue.Queue" = queue.Queue()
        self._lock = named_lock("ckpt.writer")
        self._pending: List[Tuple[str, threading.Event]] = []
        self._error: Optional[BaseException] = None
        self._error_path: Optional[str] = None
        self._log = log or (
            lambda msg: print(f"[ckpt] {msg}", flush=True)
        )
        self._thread = threading.Thread(
            target=self._worker, name="ckpt-async-writer", daemon=True
        )
        self._thread.start()

    def _worker(self):
        metrics = get_metrics()
        while True:
            item = self._q.get()
            if item is None:
                return
            path, skeleton, leaves, done, steps_at_submit = item
            try:
                import time as _time

                from distributed_machine_learning_tpu import obs

                t0 = _time.time()
                with obs.span("ckpt.save_async", {"path": path}):
                    nbytes, nchunks = fmt.write_snapshot(
                        path, skeleton, leaves
                    )
                metrics.record_save(
                    _time.time() - t0, nbytes, max(nchunks, 1)
                )
                metrics.record_async_completion(steps_at_submit)
            except BaseException as exc:  # noqa: BLE001 - surfaced on next save
                metrics.add("save_errors")
                with self._lock:
                    self._error = exc
                    self._error_path = path
            finally:
                with self._lock:
                    self._pending = [
                        (p, ev) for p, ev in self._pending if ev is not done
                    ]
                done.set()

    def _raise_pending_error(self):
        with self._lock:
            exc, path = self._error, self._error_path
            self._error, self._error_path = None, None
        if exc is not None:
            raise RuntimeError(
                f"previous async checkpoint save to {path} failed"
            ) from exc

    def save(self, path: str, tree) -> str:
        """Snapshot ``tree`` to host NOW (per-shard; donation-safe) and
        queue the write; returns ``path`` immediately.  Raises the previous
        save's error, if any, before doing anything."""
        self._raise_pending_error()
        import time as _time

        t0 = _time.time()
        skeleton, leaves = fmt.snapshot_tree(tree)
        metrics = get_metrics()
        metrics.add("save_block_s", _time.time() - t0)
        done = threading.Event()
        with self._lock:
            self._pending.append((path, done))
        self._q.put((path, skeleton, leaves, done, metrics.step_count()))
        return path

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        """Barrier: block until every queued write is durable; re-raise the
        first unclaimed write error.  Returns False on timeout."""
        import time as _time

        # Monotonic: this is a wait DEADLINE — a wall-clock step must not
        # stretch or collapse the barrier (dmlint DML004).
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._lock:
            events = [ev for _, ev in self._pending]
        for ev in events:
            left = None if deadline is None else deadline - _time.monotonic()
            if left is not None and left <= 0:
                return False
            if not ev.wait(left):
                return False
        self._raise_pending_error()
        return True

    def pending_paths(self) -> List[str]:
        with self._lock:
            return [p for p, _ in self._pending]

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Flush (bounded) and stop the worker; unclaimed errors are logged
        rather than lost."""
        if not self._thread.is_alive():
            return
        try:
            flushed = self.wait_until_finished(timeout=timeout)
        except BaseException as exc:  # noqa: BLE001 - teardown must not die
            self._log(f"WARNING: async checkpoint write failed: {exc!r}")
            flushed = True
        if not flushed:
            self._log(
                f"WARNING: abandoning hung checkpoint write(s) at "
                f"teardown: {self.pending_paths()[:3]}"
            )
        self._q.put(None)
        if flushed:
            self._thread.join(timeout=10)
