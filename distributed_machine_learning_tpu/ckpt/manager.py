"""Generation management: retention, committed-fallback, cleanup.

THE owner of checkpoint-generation semantics for both on-disk formats:

* ``ckpt_NNNNNN.msgpack`` — the legacy single-blob format
  (``tune/checkpoint.py``, which now delegates its generation walking
  here and stays as the compatibility shim);
* ``gen_NNNNNN/`` — the sharded chunked format (``ckpt/format.py``).

Both can coexist in one directory (a trial upgraded mid-experiment keeps
restoring), ordered by step.  "Valid" means: passes its integrity check —
a sharded generation must be COMMITTED (chunks -> index -> COMMIT all
landed) and checksum-clean; a msgpack file must match its manifest sidecar
and decode.

:class:`CheckpointManager` wraps one directory with save (sync or async),
newest-committed-valid restore fallback, retention, and
uncommitted-generation cleanup on start — the lifecycle every driver
(executors, cluster requeue, vectorized populations) routes through.
"""

from __future__ import annotations

import posixpath
import re
import time
from typing import Any, Dict, List, Optional, Tuple

from distributed_machine_learning_tpu.ckpt import format as fmt
from distributed_machine_learning_tpu.ckpt.metrics import get_metrics
from distributed_machine_learning_tpu.ckpt.writer import AsyncCheckpointer
from distributed_machine_learning_tpu.tune.storage import get_storage

MSGPACK_RE = re.compile(r"^ckpt_(\d+)\.msgpack$")

FORMATS = ("msgpack", "sharded")


def _legacy():
    # Function-level import: tune.checkpoint imports this module's helpers
    # (the shim direction); the reverse edge must stay lazy.
    from distributed_machine_learning_tpu.tune import checkpoint as ckpt_lib

    return ckpt_lib


def step_of_path(path: str) -> int:
    """Step encoded in a checkpoint path of either format (0 if neither)."""
    base = posixpath.basename(str(path).rstrip("/"))
    m = MSGPACK_RE.match(base)
    if m:
        return int(m.group(1))
    m = fmt.GEN_RE.match(base)
    return int(m.group(1)) if m else 0


def step_path(directory: str, step: int, checkpoint_format: str = "msgpack") -> str:
    """The canonical path of generation ``step`` under ``directory``."""
    if checkpoint_format not in FORMATS:
        raise ValueError(
            f"checkpoint_format must be one of {FORMATS}, "
            f"got {checkpoint_format!r}"
        )
    backend, d = get_storage(directory)
    name = (
        f"ckpt_{int(step):06d}.msgpack"
        if checkpoint_format == "msgpack"
        else fmt.generation_name(step)
    )
    return backend.join(d, name)


def list_generations(directory: str) -> List[Tuple[int, str, str]]:
    """Sorted ``(step, full_path, kind)`` for every generation of either
    format under ``directory`` (kind in :data:`FORMATS`)."""
    backend, d = get_storage(directory)
    out: List[Tuple[int, str, str]] = []
    for name in backend.listdir(d):
        m = MSGPACK_RE.match(name)
        if m:
            out.append((int(m.group(1)), backend.join(d, name), "msgpack"))
            continue
        m = fmt.GEN_RE.match(name)
        if m:
            out.append((int(m.group(1)), backend.join(d, name), "sharded"))
    return sorted(out, key=lambda e: (e[0], e[2]))


def latest_generation(directory: str) -> Tuple[Optional[str], int]:
    """(path, step) of the newest generation BY NAME (no integrity check),
    or (None, 0)."""
    gens = list_generations(directory)
    if not gens:
        return None, 0
    step, path, _ = gens[-1]
    return path, step


def newest_valid_generation(
    directory: str, max_step: Optional[int] = None
) -> Tuple[Optional[str], int]:
    """(path, step) of the newest generation that passes its integrity
    check (committed + checksum-clean), or (None, 0).

    ``max_step`` bounds the search: generations ABOVE it are skipped —
    the at-least-once fencing guard (a fenced zombie incarnation saves
    its checkpoint BEFORE its report frame, so at requeue time the newest
    valid generation can be one the driver never saw reported; restoring
    it would skip that report forever).  Callers pass the trial's last
    REPORTED iteration."""
    for step, path, kind in reversed(list_generations(directory)):
        if max_step is not None and step > max_step:
            continue
        if kind == "sharded":
            if fmt.is_committed(path):
                return path, step
        elif _legacy().verify_checkpoint(path):
            return path, step
    return None, 0


# Quarantined generations are renamed under this prefix: the name no
# longer matches MSGPACK_RE / GEN_RE, so every generation walk (restore
# fallback, retention, resume discovery) is blind to them — but the bytes
# stay on storage for forensics until retention-by-hand removes them.
QUARANTINE_PREFIX = "fenced"


def quarantine_generations_above(
    directory: str, step: int, tag: str = "", log=None
) -> int:
    """Rename (quarantine) every generation with step > ``step``.

    The at-least-once fencing fix (docs/operations.md): when a trial is
    requeued off a fenced/expired incarnation, any checkpoint NEWER than
    its last reported iteration was written by the zombie for an epoch
    the driver never processed.  Left in place, a later corruption
    fallback — or the requeue's own newest-valid scan — could restore
    past the last report and the retry would never re-report that epoch.
    Renaming moves them out of every generation pattern while keeping the
    bytes for forensics.  Storage backends have no rename, so this is
    copy+delete per file — on the driver, off the hot path.  Returns the
    number of generations quarantined.
    """
    emit = log or (lambda msg: print(f"[ckpt] {msg}", flush=True))
    backend, d = get_storage(directory)
    suffix = f".{tag}" if tag else ""
    count = 0
    for gstep, full, kind in list_generations(directory):
        if gstep <= step:
            continue
        base = posixpath.basename(full.rstrip("/"))
        dest = backend.join(d, f"{QUARANTINE_PREFIX}{suffix}.{base}")
        if kind == "msgpack":
            data = backend.read_bytes(full)
            if data is not None:
                backend.write_bytes(dest, data)
            man = _legacy().manifest_path_for(full)
            mdata = backend.read_bytes(man)
            if mdata is not None:
                backend.write_bytes(
                    _legacy().manifest_path_for(dest), mdata
                )
            backend.delete(man)
            backend.delete(full)
        else:
            # Sharded generation: drop the COMMIT first so a racing
            # reader sees "uncommitted" (= nonexistent), never torn.
            names = fmt.list_files(full)
            ordered = sorted(
                names, key=lambda n: (n != fmt.COMMIT_NAME, n)
            )
            for name in ordered:
                src_p = backend.join(full, name)
                data = backend.read_bytes(src_p)
                if data is not None:
                    backend.write_bytes(backend.join(dest, name), data)
                backend.delete(src_p)
            import os as _os

            if _os.path.isdir(full):  # local scheme: clear the empty dir
                try:
                    _os.rmdir(full)
                except OSError:
                    pass
        emit(
            f"quarantined unreported generation {base} (step {gstep} > "
            f"last reported {step}) -> {posixpath.basename(dest)}"
        )
        count += 1
    if count:
        get_metrics().add("generations_quarantined", count)
    return count


def restore_with_fallback(
    path: Optional[str], directory: Optional[str] = None, log=None,
    shardings=None,
) -> Tuple[Optional[Dict[str, Any]], Optional[str], int]:
    """Restore ``path``; on corruption (torn sharded save, bad checksum,
    undecodable blob) fall back to the newest VALID generation under
    ``directory``.  Returns ``(tree, used_path, used_step)`` —
    ``(None, None, 0)`` when nothing restorable survives."""
    emit = log or (lambda msg: print(f"[ckpt] {msg}", flush=True))
    load = _legacy().load_checkpoint
    metrics = get_metrics()
    if not path:
        # No restore target = a fresh trial; never restore one by accident.
        return None, None, 0
    try:
        tree = load(path, shardings=shardings)
        if tree is not None:
            return tree, path, step_of_path(path)
        emit(f"restore target {path} is missing")
    except fmt.CheckpointCorruptionError as exc:
        emit(f"restore target is corrupt: {exc}")
    if not directory:
        return None, None, 0
    fell_back = False
    for step, full, _kind in reversed(list_generations(directory)):
        if full == path:
            continue  # already tried (and failed) above
        try:
            tree = load(full, shardings=shardings)
        except fmt.CheckpointCorruptionError as exc:
            emit(f"skipping corrupt generation {full}: {exc}")
            metrics.add("corrupt_generations_skipped")
            fell_back = True
            continue
        if tree is not None:
            emit(f"fell back to valid generation {full} (step={step})")
            metrics.add("restore_fallbacks")
            return tree, full, step
    if fell_back:
        metrics.add("restore_fallbacks")
    return None, None, 0


def prune_generations(directory: str, keep: int, protect=None,
                      pending_latest: Optional[str] = None) -> int:
    """Keep only the ``keep`` newest generations (either format) under
    ``directory``; semantics match the legacy
    ``tune.checkpoint.prune_checkpoints`` (protect set, in-flight
    ``pending_latest`` alias).  Returns generations deleted."""
    if keep <= 0:
        return 0
    if protect is None:
        protected = set()
    elif isinstance(protect, str):
        protected = {protect}
    else:
        protected = set(protect)
    if pending_latest is not None:
        protected.add(pending_latest)
    gens = list_generations(directory)
    excess = gens[:-keep] if len(gens) > keep else []
    backend, _ = get_storage(directory)
    deleted = 0
    for _step, full, kind in excess:
        if full in protected:
            continue
        if kind == "sharded":
            fmt.delete_generation(full)
        else:
            backend.delete(full)
            # Integrity sidecar rides with its checkpoint (absent for
            # legacy generations; delete is a no-op then).
            backend.delete(_legacy().manifest_path_for(full))
        deleted += 1
    if deleted:
        get_metrics().add("generations_pruned", deleted)
    return deleted


def cleanup_uncommitted(directory: str, log=None) -> int:
    """Delete sharded generations without a valid COMMIT — the debris of a
    preempted save.  ONLY safe at start (driver/worker boot, experiment
    resume), before any writer is live: an in-flight async save looks
    exactly like debris until its COMMIT lands.  Returns count removed."""
    emit = log or (lambda msg: print(f"[ckpt] {msg}", flush=True))
    removed = 0
    for _step, full, kind in list_generations(directory):
        if kind != "sharded" or fmt.is_committed(full):
            continue
        fmt.delete_generation(full)
        emit(f"removed uncommitted generation {full}")
        removed += 1
    if removed:
        get_metrics().add("uncommitted_cleaned", removed)
    return removed


class CheckpointManager:
    """Generations under one directory: save / restore / retention.

    ``checkpoint_format`` picks what :meth:`save` writes; restore handles
    both formats regardless (a directory can hold a mixed history).
    ``async_save`` overlaps serialization+I/O with training (snapshot on
    the caller, write on a background thread; ``wait_until_finished`` is
    the barrier and a failed write surfaces on the next save).
    ``keep`` > 0 prunes to the newest K after each save.  On construction
    the manager removes uncommitted debris left by a preempted writer.
    """

    def __init__(
        self,
        directory: str,
        *,
        checkpoint_format: str = "sharded",
        keep: int = 0,
        async_save: bool = False,
        clean_on_start: bool = True,
        log=None,
    ):
        if checkpoint_format not in FORMATS:
            raise ValueError(
                f"checkpoint_format must be one of {FORMATS}, "
                f"got {checkpoint_format!r}"
            )
        self.directory = directory
        self.checkpoint_format = checkpoint_format
        self.keep = int(keep)
        self._log = log or (lambda msg: print(f"[ckpt] {msg}", flush=True))
        self._writer: Optional[AsyncCheckpointer] = None
        self._async = bool(async_save)
        self._pending_path: Optional[str] = None
        if clean_on_start:
            cleanup_uncommitted(directory, log=self._log)

    # -- paths / listing -----------------------------------------------------

    def step_path(self, step: int) -> str:
        return step_path(self.directory, step, self.checkpoint_format)

    def all_steps(self) -> List[int]:
        return [s for s, _p, _k in list_generations(self.directory)]

    def latest(self) -> Tuple[Optional[str], int]:
        return latest_generation(self.directory)

    def newest_valid(self) -> Tuple[Optional[str], int]:
        return newest_valid_generation(self.directory)

    # -- save ----------------------------------------------------------------

    def save(self, step: int, tree, wait: bool = False) -> str:
        """Write generation ``step``; returns its path.  Async unless the
        manager is synchronous or ``wait=True``."""
        path = self.step_path(step)
        if self._async and not wait:
            if self._writer is None:
                self._writer = AsyncCheckpointer(log=self._log)
            if self.checkpoint_format == "msgpack":
                # The legacy blob writer is synchronous by design (its
                # async path lives in tune.checkpoint.AsyncCheckpointWriter
                # used by the executors); snapshot-now semantics only exist
                # for the sharded format.
                self._save_sync(path, tree)
            else:
                self._writer.save(path, tree)
            self._pending_path = path
        else:
            self._save_sync(path, tree)
            self._pending_path = None
        if self.keep > 0:
            try:
                prune_generations(
                    self.directory, self.keep,
                    pending_latest=self._pending_path,
                )
            except Exception as exc:  # noqa: BLE001 - retention never kills
                self._log(f"retention prune failed: {exc!r}")
        return path

    def _save_sync(self, path: str, tree) -> None:
        if self.checkpoint_format == "sharded":
            fmt.save_sharded(path, tree)
        else:
            t0 = time.time()
            _legacy().save_checkpoint(path, tree)
            # save_checkpoint records its own bytes; wall only here would
            # double count, so nothing extra to do.
            del t0

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        if self._writer is None:
            return True
        ok = self._writer.wait_until_finished(timeout=timeout)
        if ok:
            self._pending_path = None
        return ok

    # -- restore -------------------------------------------------------------

    def restore(
        self, path: Optional[str] = None, shardings=None,
    ) -> Tuple[Optional[Dict[str, Any]], Optional[str], int]:
        """Restore ``path`` (default: the newest generation), falling back
        to older VALID generations on corruption.  ``shardings`` reshards
        array leaves onto a target mesh (see ``ckpt.format.load_sharded``).
        """
        self.wait_until_finished(timeout=120.0)
        if path is None:
            path, _ = self.latest()
            if path is None:
                return None, None, 0
        return restore_with_fallback(
            path, self.directory, log=self._log, shardings=shardings,
        )

    # -- retention / teardown -------------------------------------------------

    def prune(self, keep: Optional[int] = None, protect=None,
              pending_latest: Optional[str] = None) -> int:
        return prune_generations(
            self.directory, self.keep if keep is None else keep,
            protect=protect,
            pending_latest=pending_latest or self._pending_path,
        )

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
