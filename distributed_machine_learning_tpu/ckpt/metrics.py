"""Process-wide checkpoint I/O counters.

Orbax's position (PAPERS.md) is that checkpoint save/restore time is a
first-order training cost — which makes it a first-order *metric*: a sweep
that stalls behind synchronous writes should show it in numbers, not in a
hunch.  One registry for the whole process (both checkpoint formats, every
driver) so the runner/cluster/vectorized teardowns can publish a
``checkpoint`` block into ``experiment_state.json`` and TensorBoard next to
the liveness and fault counters.

Drivers scope the process-wide totals to one run by snapshotting at start
and writing :meth:`CheckpointMetrics.delta_since` at teardown.

The async-overlap accounting is counter-based (no clocks): every report
boundary calls :func:`note_step`; an async save records the step counter at
submit and, when its write completes, the steps that elapsed in between —
``async_overlapped_steps`` > 0 is the proof that training ran while the
write was in flight.
"""

from __future__ import annotations

import threading
from typing import Dict
from distributed_machine_learning_tpu.analysis.locks import named_lock


class CheckpointMetrics:
    """Thread-safe counter registry for checkpoint save/restore activity."""

    _FIELDS = (
        "saves",
        "save_bytes",
        "save_wall_s",
        "save_block_s",
        "chunks_written",
        "save_errors",
        "async_saves",
        "async_saves_overlapping",
        "async_overlapped_steps",
        "steps",
        "restores",
        "restore_bytes",
        "restore_wall_s",
        "restore_fallbacks",
        "corrupt_generations_skipped",
        "uncommitted_cleaned",
        "generations_pruned",
    )

    def __init__(self):
        self._lock = named_lock("ckpt.metrics")
        self._c: Dict[str, float] = {k: 0 for k in self._FIELDS}

    def add(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + value

    def note_step(self) -> int:
        """One training step boundary passed; returns the new step count.
        Called at every report/dispatch boundary by the drivers."""
        with self._lock:
            self._c["steps"] += 1
            return int(self._c["steps"])

    def step_count(self) -> int:
        with self._lock:
            return int(self._c["steps"])

    def record_save(self, wall_s: float, nbytes: int, chunks: int = 1) -> None:
        with self._lock:
            self._c["saves"] += 1
            self._c["save_wall_s"] += wall_s
            self._c["save_bytes"] += nbytes
            self._c["chunks_written"] += chunks

    def record_restore(self, wall_s: float, nbytes: int) -> None:
        with self._lock:
            self._c["restores"] += 1
            self._c["restore_wall_s"] += wall_s
            self._c["restore_bytes"] += nbytes

    def record_async_completion(self, steps_at_submit: int) -> None:
        """An async write became durable; credit the training steps that
        happened while it was in flight."""
        with self._lock:
            overlapped = max(int(self._c["steps"]) - steps_at_submit, 0)
            self._c["async_saves"] += 1
            self._c["async_overlapped_steps"] += overlapped
            if overlapped > 0:
                self._c["async_saves_overlapping"] += 1

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self._c.items()
            }

    def delta_since(self, baseline: Dict[str, float]) -> Dict[str, float]:
        """Counters accumulated since ``baseline`` (a prior snapshot) —
        how a driver scopes the process-wide registry to one run."""
        snap = self.snapshot()
        return {
            k: round(v - baseline.get(k, 0), 4)
            for k, v in snap.items()
        }

    def reset(self) -> None:
        """Test hook: zero every counter."""
        with self._lock:
            self._c = {k: 0 for k in self._FIELDS}


_metrics = CheckpointMetrics()

# The unified observability plane sees the same counters (obs/registry.py):
# the blocks drivers publish stay byte-identical, this just makes them
# visible in one place (flight dumps, /metrics "obs", head aggregation).
from distributed_machine_learning_tpu.obs.registry import (  # noqa: E402
    get_registry as _obs_registry,
)

_obs_registry().register_family("checkpoint", _metrics)


def get_metrics() -> CheckpointMetrics:
    """The process-wide registry (one per process, like the compile
    tracker in ``utils/compile_cache.py``)."""
    return _metrics


def note_step() -> int:
    return _metrics.note_step()
