"""Topology-portable placement: a host pytree onto ANY target sharding.

``ckpt/format.py`` proved the restore half of topology portability — a
generation saved on one mesh restores onto another via
``jax.make_array_from_callback``, reading only intersecting chunks.  This
module is the same mechanism for trees that are ALREADY on the host:
a servable bundle's msgpack params (``serve/export.py`` always gathers to
full host arrays so the bundle needs no mesh to load), which a serving
gang must lay back out over its own process-spanning mesh.  One placement
path serves both directions:

* train on 2x4, export, serve on a 2-process gang — the bundle's host
  arrays shard out over the serving mesh;
* train on one device, export, serve sharded — same call, the serving
  topology alone decides the layout.

Each process's callback slices exactly the shards its devices address, so
no member ever materializes a peer's slice on device — the
``stage_global`` contract applied leaf-wise to a params tree.
"""

from __future__ import annotations

from typing import Any

import numpy as np


def place_tree(tree: Any, shardings: Any) -> Any:
    """Place a host pytree onto a pytree of shardings (same structure;
    ``None`` entries stay host-side).  Array leaves become ``jax.Array``s
    laid out for the target mesh via ``jax.make_array_from_callback``;
    non-array leaves pass through untouched.

    Must be called by EVERY process of the target mesh (array creation
    over a process-spanning sharding is collective in effect: each
    process builds its addressable shards of the same global value).
    """
    import jax

    def place(leaf, sharding):
        if sharding is None or not hasattr(leaf, "shape"):
            return leaf
        arr = np.asarray(leaf)
        if jax.process_count() == 1:
            return jax.device_put(arr, sharding)
        return jax.make_array_from_callback(
            tuple(arr.shape), sharding, lambda idx, a=arr: a[idx]
        )

    return jax.tree_util.tree_map(place, tree, shardings)


def serving_shardings(config: Any, variables: Any, mesh) -> Any:
    """The target layout for a bundle's variables on a serving mesh:
    the model family's partition-rule table (``models/partition_rules``)
    resolved against the actual leaves — the same table training sharded
    under, so a served forward pass runs the layout it was trained with.
    """
    from distributed_machine_learning_tpu.models.partition_rules import (
        rules_for,
    )
    from distributed_machine_learning_tpu.parallel.partition import (
        shardings_from_rules,
    )

    return shardings_from_rules(variables, mesh, rules_for(config))


def reshard_onto_mesh(config: Any, variables: Any, mesh) -> Any:
    """``place_tree`` + ``serving_shardings`` in one call — the bundle
    loader's resharding route (``serve/export.load_bundle(mesh=...)``)."""
    return place_tree(variables, serving_shardings(config, variables, mesh))
