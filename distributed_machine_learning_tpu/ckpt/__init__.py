"""Distributed checkpointing: async, sharded, resharding-on-restore.

The subsystem the Orbax paper (PAPERS.md) argues production JAX training
stands on, grown natively here:

* :mod:`~distributed_machine_learning_tpu.ckpt.format` — per-shard chunk
  files + JSON index + atomic COMMIT marker; no pickle, topology-portable;
* :mod:`~distributed_machine_learning_tpu.ckpt.writer` — async saves
  (snapshot on the caller, serialize/write in the background);
* :mod:`~distributed_machine_learning_tpu.ckpt.manager` — generations,
  retention, newest-committed-valid fallback, uncommitted cleanup;
* :mod:`~distributed_machine_learning_tpu.ckpt.metrics` — save/restore
  wall, bytes, and async-overlap counters (published by every driver into
  ``experiment_state.json["checkpoint"]`` and TensorBoard).

``tune/checkpoint.py`` remains the compatibility shim over the legacy
msgpack blobs; its generation logic now routes through this package, so a
trial directory can mix both formats and every restore path (retry,
cluster requeue, serve export) handles either.
"""

from distributed_machine_learning_tpu.ckpt.format import (  # noqa: F401
    CheckpointCorruptionError,
    COMMIT_NAME,
    INDEX_NAME,
    generation_name,
    is_committed,
    is_sharded_path,
    load_sharded,
    save_sharded,
)
from distributed_machine_learning_tpu.ckpt.manager import (  # noqa: F401
    CheckpointManager,
    cleanup_uncommitted,
    latest_generation,
    list_generations,
    newest_valid_generation,
    prune_generations,
    restore_with_fallback,
    step_of_path,
    step_path,
)
from distributed_machine_learning_tpu.ckpt.metrics import (  # noqa: F401
    get_metrics,
    note_step,
)
from distributed_machine_learning_tpu.ckpt.reshard import (  # noqa: F401
    place_tree,
    reshard_onto_mesh,
    serving_shardings,
)
from distributed_machine_learning_tpu.ckpt.writer import (  # noqa: F401
    AsyncCheckpointer,
)
