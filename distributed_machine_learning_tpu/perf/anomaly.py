"""Step-stream anomaly detection: robust z-scores over per-step timings.

The obs plane records *where* time went; this module watches *whether any
of it was abnormal* — the fail-slow shapes every postmortem in this repo
shares (a wedged relay that doubles step time, a CPU-starved producer
that starves one trial, one gang member 3x slower than its peers):

* :class:`StepAnomalyDetector` — per-program-key sliding windows of step
  durations judged by **median/MAD robust z-score** (mean/std would let
  the outliers being hunted drag the threshold toward themselves).  The
  feeders: both trainables' per-epoch timings (per-trial outliers in a
  sweep — the window is shared across trials of one program class, the
  observation is attributed to a trial id), and the serve plane's
  ``engine.step`` flushes via the continuous batcher's existing per-
  bucket EWMA loop (``serve/batcher.py``).
* :class:`GangSkewMonitor` — per-round, per-member timings of one
  process-spanning trial (``multihost.check_gang_skew`` allgathers each
  member's epoch wall); a member sustained above the peer median is a
  named straggler.

A single outlier increments ``perf_anomaly_events``; ``sustain``
consecutive anomalies from the SAME attribution increment
``perf_anomaly_sustained`` plus a per-culprit counter
(``perf_straggler[<who>]`` — the trial or process id IS in the counter
name) and trigger one flight-recorder dump naming the slow member/trial
(``obs.dump_flight_recorder``).  Detection must never raise into a hot
path; every surface here is telemetry-grade.

Stdlib-only (no jax, no numpy): importable from the linter and serve
plane alike.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from distributed_machine_learning_tpu.analysis.locks import named_lock

DEFAULT_WINDOW = 64
DEFAULT_Z_THRESHOLD = 4.0
DEFAULT_SUSTAIN = 3
MIN_SAMPLES = 5

# 0.6745 ~= Phi^-1(0.75): scales MAD to the sigma of a normal, the
# standard robust-z convention (Iglewicz & Hoaglin).
_MAD_SCALE = 0.6745


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class RobustWindow:
    """A bounded window of recent durations with median/MAD z-scores.

    Bounded by construction (``deque(maxlen=...)``): a detector that
    accumulated every step of a month-long soak would be the PR 8
    ring-buffer bug wearing a new hat (dmlint DML017)."""

    def __init__(self, capacity: int = DEFAULT_WINDOW):
        if capacity < MIN_SAMPLES:
            raise ValueError(
                f"capacity must be >= {MIN_SAMPLES}: {capacity}"
            )
        self._vals: deque = deque(maxlen=int(capacity))

    def add(self, value: float) -> None:
        self._vals.append(float(value))

    def __len__(self) -> int:
        return len(self._vals)

    def median(self) -> Optional[float]:
        return _median(list(self._vals)) if self._vals else None

    def zscore(self, value: float) -> Optional[float]:
        """Robust z of ``value`` vs the window (None below MIN_SAMPLES).
        A zero MAD (near-identical timings) falls back to a 5%-of-median
        scale so a genuinely flat stream still scores a 2x step as
        anomalous instead of dividing by zero."""
        vals = list(self._vals)
        if len(vals) < MIN_SAMPLES:
            return None
        med = _median(vals)
        mad = _median([abs(v - med) for v in vals])
        # The floor keeps a degenerate window (near-zero median from
        # clamped measurements) from manufacturing astronomic z-scores:
        # below it, nothing is judged anomalous by a sub-microsecond gap.
        scale = mad / _MAD_SCALE if mad > 0 else max(
            abs(med) * 0.05, 1e-6
        )
        return (float(value) - med) / scale


class StepAnomalyDetector:
    """Windowed per-key anomaly detection with sustained-culprit naming.

    ``observe(key, seconds, who=...)`` returns an anomaly dict for a
    SLOW outlier (fast outliers are left alone — the hunt is for
    stragglers, and a suspiciously fast step shows up in correctness
    tests, not here), and None otherwise.
    """

    def __init__(
        self,
        window: int = DEFAULT_WINDOW,
        z_threshold: float = DEFAULT_Z_THRESHOLD,
        sustain: int = DEFAULT_SUSTAIN,
    ):
        self.window = int(window)
        self.z_threshold = float(z_threshold)
        self.sustain = max(int(sustain), 1)
        self._lock = named_lock("perf.anomaly")
        self._windows: Dict[str, RobustWindow] = {}
        self._streaks: Dict[Tuple[str, Optional[str]], int] = {}
        self.anomalies = 0
        self.sustained = 0
        self.observations = 0

    def observe(
        self, key: str, seconds: float, who: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        from distributed_machine_learning_tpu import obs

        try:
            with self._lock:
                self.observations += 1
                w = self._windows.get(key)
                if w is None:
                    w = self._windows[key] = RobustWindow(self.window)
                z = w.zscore(seconds)
                med = w.median()
                w.add(seconds)
                streak_key = (key, who)
                if z is not None and z >= self.z_threshold:
                    self.anomalies += 1
                    streak = self._streaks.get(streak_key, 0) + 1
                    self._streaks[streak_key] = streak
                    if streak == self.sustain:
                        self.sustained += 1
                else:
                    self._streaks.pop(streak_key, None)
                    return None
            reg = obs.get_registry()
            reg.add("perf_anomaly_events")
            anomaly = {
                "program": key,
                "who": who,
                "seconds": round(float(seconds), 6),
                "median_s": round(med, 6) if med is not None else None,
                "zscore": round(z, 2),
                "streak": streak,
                "sustained": streak >= self.sustain,
            }
            obs.event("perf_anomaly", anomaly)
            if streak == self.sustain:
                # Fire the heavy forensics ONCE per streak (the streak
                # counter keeps growing, the dump does not repeat).
                reg.add("perf_anomaly_sustained")
                if who is not None:
                    reg.add(f"perf_straggler[{who}]")
                obs.dump_flight_recorder(
                    f"perf_anomaly_{key}", extra=anomaly
                )
            return anomaly
        except Exception:  # noqa: BLE001 - never fail the timed hot path
            obs.get_registry().add("perf_anomaly_errors")
            return None

    def snapshot(self) -> Dict[str, float]:
        """The ``perf`` registry family: detector health at a glance."""
        with self._lock:
            return {
                "observations": self.observations,
                "anomalies": self.anomalies,
                "sustained": self.sustained,
                "programs_watched": len(self._windows),
            }

    def reset(self) -> None:
        """Test hook: drop every window and streak."""
        with self._lock:
            self._windows.clear()
            self._streaks.clear()
            self.anomalies = self.sustained = self.observations = 0


def skew_by_member(
    values: Dict[Any, float], ratio_threshold: float = 1.75
) -> List[Tuple[Any, float]]:
    """Members whose timing exceeds ``ratio_threshold`` x the median of
    their PEERS (median excludes the candidate, so one straggler in a
    2-member gang is still visible).  Returns ``[(member, ratio), ...]``
    sorted slowest-first; empty for a healthy round."""
    if len(values) < 2:
        return []
    out: List[Tuple[Any, float]] = []
    for member, v in values.items():
        peers = [x for m, x in values.items() if m != member]
        med = _median(peers)
        if med <= 0:
            continue
        ratio = float(v) / med
        if ratio >= ratio_threshold:
            out.append((member, round(ratio, 3)))
    out.sort(key=lambda t: t[1], reverse=True)
    return out


class GangSkewMonitor:
    """Sustained per-gang-member skew over successive rounds (epochs).

    Pure bookkeeping — the collectives that gather each member's timing
    live in ``multihost.runtime.check_gang_skew``; this class just
    judges the per-round ``{process_id: seconds}`` map so it is testable
    without a process-spanning runtime."""

    def __init__(
        self,
        ratio_threshold: float = 1.75,
        sustain: int = 2,
        gang_id: Optional[str] = None,
    ):
        self.ratio_threshold = float(ratio_threshold)
        self.sustain = max(int(sustain), 1)
        self.gang_id = gang_id
        self._lock = named_lock("perf.gangskew")
        self._streaks: Dict[Any, int] = {}
        self.rounds = 0
        self.straggler_rounds = 0

    def observe_round(
        self,
        values: Dict[Any, float],
        label: str = "epoch",
        report: bool = True,
    ) -> List[Tuple[Any, float]]:
        """Judge one round; ``report=False`` (non-coordinator gang
        members) still tracks streaks but leaves counters and dumps to
        the coordinator so the head sees each incident exactly once."""
        from distributed_machine_learning_tpu import obs

        stragglers = skew_by_member(values, self.ratio_threshold)
        newly_sustained = []
        with self._lock:
            self.rounds += 1
            if stragglers:
                self.straggler_rounds += 1
            flagged = {m for m, _ in stragglers}
            for m in list(self._streaks):
                if m not in flagged:
                    self._streaks.pop(m)
            for m, ratio in stragglers:
                streak = self._streaks.get(m, 0) + 1
                self._streaks[m] = streak
                if streak == self.sustain:
                    newly_sustained.append((m, ratio))
        if report and newly_sustained:
            reg = obs.get_registry()
            for member, ratio in newly_sustained:
                reg.add("perf_anomaly_sustained")
                reg.add(f"perf_straggler[process_{member}]")
                detail = {
                    "label": label,
                    "gang_id": self.gang_id,
                    "process_id": member,
                    "ratio_vs_peer_median": ratio,
                    "round_timings_s": {
                        str(k): round(float(v), 6)
                        for k, v in values.items()
                    },
                }
                obs.event("perf_gang_skew", detail)
                obs.dump_flight_recorder(
                    f"perf_gang_skew_p{member}", extra=detail
                )
        return stragglers

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "rounds": self.rounds,
                "straggler_rounds": self.straggler_rounds,
            }


_detector: Optional[StepAnomalyDetector] = None
_detector_lock = threading.Lock()  # creation only


def get_step_anomalies() -> StepAnomalyDetector:
    """The process-wide detector (registered as the ``perf`` family in
    the metrics registry, same discipline as ``compilecache.counters``)."""
    global _detector
    if _detector is None:
        with _detector_lock:
            if _detector is None:
                det = StepAnomalyDetector()
                from distributed_machine_learning_tpu.obs import (
                    get_registry,
                )

                get_registry().register_family("perf", det)
                _detector = det
    return _detector
