"""XLA cost-model audit: captured program costs, analytic cross-check,
roofline classification, and the one per-epoch MFU accounting helper.

The analytic FLOP model (``ops/flops.py``) has been caught understating
work twice (advisor r3: the GQA projection terms, the remat backward
factor) — and every time it drifts, the reported MFU silently inflates.
XLA already computes the ground truth at compile time:
``compiled.cost_analysis()`` reports the FLOPs and bytes the scheduled
program actually performs.  This module makes that number a first-class
artifact:

* **Capture** — :func:`record_program_cost` is called by the AOT
  executable cache (``compilecache/aot.py``) on executables it was
  compiling *anyway*, so the audit adds ZERO compiles.  The cost record
  is written as a ``<key>.cost.json`` sidecar next to the serialized
  executable, and a cached-artifact install reads the sidecar back
  instead of re-deriving anything (:func:`load_program_cost`).
* **Cross-check** — :func:`crosscheck` compares the captured FLOPs
  against the analytic estimate; divergence beyond tolerance in EITHER
  direction is a counted, evented finding (the class of bug that
  inflated MFU before).
* **Roofline** — :func:`roofline` classifies a program compute- vs
  memory-bound from arithmetic intensity (flops / bytes accessed) vs the
  device's ridge point (peak FLOP/s / HBM bandwidth), so per-epoch
  records can say not just *how fast* but *what the ceiling is*.
* **One MFU helper** — :class:`EpochPerfAccounting` owns the per-epoch
  flops/peak/MFU derivation both trainables used to duplicate, keeps the
  record keys byte-compatible (``epoch_time_s``, ``device_bytes_in_use``,
  ``epoch_flops``, ``mfu``; rounding included), adds ``roofline_bound``
  where a captured cost exists, and feeds the step-stream anomaly
  detector (``perf/anomaly.py``).

Stdlib-only at import time (no jax): the sentinel CLI and the linter can
import ``perf`` on hosts with a broken backend.  The only jax objects
ever touched are the ``compiled`` executables callers already hold.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, Optional

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.ops.flops import (
    device_peak_flops,
    epoch_flops as _epoch_flops,
)

# Peak HBM bandwidth per chip (bytes/s), by ``device_kind`` substring —
# same lookup discipline as ops/flops._PEAK_BF16 (public spec sheets).
_HBM_BYTES_PER_S = (
    ("v6", 1640e9),      # Trillium
    ("v5p", 2765e9),
    ("v5 lite", 819e9),  # v5e reports device_kind "TPU v5 lite"
    ("v5e", 819e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)

# Divergence tolerance for analytic-vs-captured FLOPs, as a ratio band:
# measured/analytic outside [1/(1+tol), (1+tol)] is a finding.  The
# analytic model is matmul-only (deliberately conservative) and XLA's
# count includes elementwise work plus fusion effects, so the band is
# wide — it exists to catch MISSING TERMS (the 3x-vs-4x remat class,
# a forgotten projection), not rounding.
DEFAULT_CROSSCHECK_TOL = 1.0


def device_hbm_bandwidth(device) -> Optional[float]:
    """Peak HBM bytes/s of ``device`` (None when unknown — e.g. CPU)."""
    if device is None or getattr(device, "platform", None) != "tpu":
        return None
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key, bw in _HBM_BYTES_PER_S:
        if key in kind:
            return bw
    return None


# -- capture -----------------------------------------------------------------


def extract_cost(compiled) -> Optional[Dict[str, float]]:
    """The JSON-able cost record of a compiled executable, or None when
    the backend/executable exposes no cost analysis.  Never raises —
    telemetry must not fail the compile path that calls it."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - backend without cost analysis
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, float] = {}
    for src, dst in (
        ("flops", "flops"),
        ("bytes accessed", "bytes_accessed"),
        ("transcendentals", "transcendentals"),
        ("optimal_seconds", "optimal_seconds"),
    ):
        v = ca.get(src)
        if isinstance(v, (int, float)) and v == v:  # drop NaNs
            out[dst] = float(v)
    return out or None


def cost_sidecar_path(directory: str, key: str) -> str:
    """``<dir>/<key>.cost.json`` — rides next to ``<key>.aotexec``."""
    return os.path.join(directory, f"{key}.cost.json")


_store_lock = named_lock("perf.costmodel")
_costs: Dict[str, Dict[str, Any]] = {}


def program_cost(key: str) -> Optional[Dict[str, Any]]:
    """The captured cost record for a program key (this process)."""
    with _store_lock:
        rec = _costs.get(key)
        return dict(rec) if rec else None


def _remember(key: str, cost: Dict[str, Any]) -> None:
    with _store_lock:
        _costs[key] = cost


def reset_cost_store() -> None:
    """Test hook: forget every captured program cost."""
    with _store_lock:
        _costs.clear()


def record_program_cost(
    key: str, compiled, directory: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """Capture ``compiled``'s cost analysis under ``key`` and (when
    ``directory`` is given) persist the sidecar.  Called by the AOT cache
    on executables it was compiling anyway — this function never compiles
    and never raises."""
    from distributed_machine_learning_tpu.compilecache.counters import (
        get_counters,
    )

    cost = extract_cost(compiled)
    if cost is None:
        return None
    rec = {"key": key, "captured_at": time.time(), **cost}
    _remember(key, rec)
    get_counters().add("cost_captures")
    if directory:
        try:
            os.makedirs(directory, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, cost_sidecar_path(directory, key))
        except OSError:
            from distributed_machine_learning_tpu.obs import get_registry

            get_registry().add("export_failures")
    return rec


def load_program_cost(key: str, directory: str) -> Optional[Dict[str, Any]]:
    """Read a cost sidecar written by another process (or an earlier run)
    into this process's store — the cached-artifact path: the executable
    was deserialized, and its cost record rides along for free."""
    from distributed_machine_learning_tpu.compilecache.counters import (
        get_counters,
    )

    try:
        with open(cost_sidecar_path(directory, key)) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(rec, dict) or "flops" not in rec:
        return None
    _remember(key, rec)
    get_counters().add("cost_sidecar_loads")
    return rec


# -- cross-check + roofline --------------------------------------------------


def crosscheck(
    analytic_flops: Optional[float],
    measured_flops: Optional[float],
    tolerance: float = DEFAULT_CROSSCHECK_TOL,
    label: str = "",
) -> Optional[Dict[str, Any]]:
    """Compare the analytic FLOP estimate against the captured one.

    Returns a finding dict when they diverge beyond ``tolerance`` in
    either direction (``kind`` names which side is wrong: an analytic
    UNDERSTATEMENT is the MFU-inflating class), else None.  Every check
    and every divergence is counted in the registry
    (``perf_costmodel_checks`` / ``perf_costmodel_divergences``)."""
    from distributed_machine_learning_tpu import obs

    if not analytic_flops or not measured_flops:
        return None
    reg = obs.get_registry()
    reg.add("perf_costmodel_checks")
    ratio = measured_flops / analytic_flops
    lo, hi = 1.0 / (1.0 + tolerance), 1.0 + tolerance
    if lo <= ratio <= hi:
        return None
    finding = {
        "kind": (
            "analytic-understates" if ratio > hi else "analytic-overstates"
        ),
        "label": label,
        "analytic_flops": float(analytic_flops),
        "measured_flops": float(measured_flops),
        "ratio": round(ratio, 4),
        "tolerance": tolerance,
    }
    reg.add("perf_costmodel_divergences")
    obs.event("costmodel_divergence", finding)
    return finding


def roofline(
    cost: Optional[Dict[str, Any]],
    peak_flops: Optional[float],
    hbm_bytes_per_s: Optional[float],
) -> Optional[Dict[str, Any]]:
    """Compute- vs memory-bound classification of one program.

    Arithmetic intensity (flops / bytes accessed) above the device ridge
    point (peak FLOP/s / HBM bytes/s) means the MXU, not HBM, is the
    ceiling.  None when the cost or device peaks are unknown."""
    if not cost or not peak_flops or not hbm_bytes_per_s:
        return None
    flops = cost.get("flops")
    bytes_accessed = cost.get("bytes_accessed")
    if not flops or not bytes_accessed:
        return None
    intensity = flops / bytes_accessed
    ridge = peak_flops / hbm_bytes_per_s
    return {
        "arithmetic_intensity": round(intensity, 3),
        "ridge_intensity": round(ridge, 3),
        "bound": "compute" if intensity >= ridge else "memory",
    }


def crosscheck_program(
    key: str,
    analytic_flops: Optional[float],
    tolerance: float = DEFAULT_CROSSCHECK_TOL,
) -> Optional[Dict[str, Any]]:
    """Cross-check a captured program cost against its analytic estimate
    — the call sites are the trainables, right after AOT resolution
    (the cost was captured or sidecar-loaded by then, or this no-ops)."""
    cost = program_cost(key)
    if cost is None:
        return None
    return crosscheck(
        analytic_flops, cost.get("flops"), tolerance=tolerance, label=key
    )


# -- the one per-epoch MFU accounting helper ---------------------------------


def program_class(
    config: Dict[str, Any], batch_size: int, seq_len: int, features: int
) -> str:
    """A short label grouping trials that run the SAME epoch program
    shape — the anomaly detector's comparison population (two trials of
    one sweep differing only in lr/wd land in the same class)."""
    return (
        f"{config.get('model', 'transformer')}"
        f"/b{int(batch_size)}s{int(seq_len)}f{int(features)}"
    )


class EpochPerfAccounting:
    """Per-epoch MFU + roofline + anomaly accounting, shared by every
    trainable (``tune/trainable.py`` resident + streaming,
    ``tune/trainable_sharded.py``).

    Record keys and rounding are byte-compatible with the blocks this
    class replaced: ``epoch_time_s`` (4 dp), ``device_bytes_in_use``
    (int), ``epoch_flops``, ``mfu`` (5 dp); ``roofline_bound`` is
    additive and only appears when a captured cost AND device peaks
    exist (never on the CPU test backend).
    """

    def __init__(
        self,
        config: Dict[str, Any],
        *,
        batch_size: int,
        seq_len: int,
        features: int,
        steps_per_epoch: int,
        eval_rows: int,
        device=None,
        num_devices: int = 1,
        program_key: Optional[str] = None,
        program_steps: Optional[int] = None,
        trial_id: Optional[str] = None,
    ):
        self.config = config
        self.steps_per_epoch = int(steps_per_epoch)
        self.epoch_flops = _epoch_flops(
            config, batch_size, seq_len, features, steps_per_epoch,
            eval_rows,
        )
        dtype = str(config.get("compute_dtype", "float32"))
        per_chip = device_peak_flops(device, dtype)
        self.peak = per_chip * max(int(num_devices), 1) if per_chip else None
        self.trial_id = trial_id
        self.program_class = program_class(
            config, batch_size, seq_len, features
        )
        self.crosscheck_finding = None
        self._roofline = None
        if program_key is not None:
            # The AOT tier captured (or sidecar-loaded) this program's
            # cost by the time the trainable built its programs; audit it
            # against the analytic model and classify the ceiling.
            from distributed_machine_learning_tpu.ops.flops import (
                train_step_flops,
            )

            step = train_step_flops(config, batch_size, seq_len, features)
            # ``program_steps``: how many train steps the AOT program
            # itself runs (a fused epoch program = steps_per_epoch; a
            # streaming chunk program = its chunk's batches).
            n_steps = (
                int(program_steps) if program_steps is not None
                else self.steps_per_epoch
            )
            analytic_program = step * n_steps if step is not None else None
            self.crosscheck_finding = crosscheck_program(
                program_key, analytic_program
            )
            hbm = device_hbm_bandwidth(device)
            self._roofline = roofline(
                program_cost(program_key),
                self.peak,
                hbm * max(int(num_devices), 1) if hbm else None,
            )

    @property
    def roofline_bound(self) -> Optional[str]:
        return self._roofline["bound"] if self._roofline else None

    def annotate(
        self,
        record: Dict[str, Any],
        exec_s: float,
        *,
        device=None,
        observe_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Stamp one epoch's perf keys onto ``record`` and feed the
        step-stream anomaly detector (``observe_s`` defaults to
        ``exec_s``; the streaming paths pass wall-including-wait so a
        starved consumer reads as slow, which is the straggler signal)."""
        record["epoch_time_s"] = round(exec_s, 4)
        # Device-memory watermark (TPU HBM; None on CPU): catches
        # per-epoch memory creep — leaked buffers, donation regressions —
        # in the ordinary metric stream where TB/analyze can plot it.
        if device is not None:
            try:
                stats = device.memory_stats()
                if stats and "bytes_in_use" in stats:
                    record["device_bytes_in_use"] = int(
                        stats["bytes_in_use"]
                    )
            except Exception:  # noqa: BLE001 - telemetry must never fail
                pass
        if self.epoch_flops is not None:
            record["epoch_flops"] = self.epoch_flops
            if self.peak:
                record["mfu"] = round(
                    self.epoch_flops / exec_s / self.peak, 5
                )
        if self._roofline is not None:
            record["roofline_bound"] = self._roofline["bound"]
        from distributed_machine_learning_tpu.perf.anomaly import (
            get_step_anomalies,
        )

        value = observe_s if observe_s is not None else exec_s
        # A compile-dominated epoch clamps wall-minus-compile to ~0
        # (tune/trainable.py's max(..., 1e-9)); a clamped measurement is
        # not a step timing and would poison the window's median with
        # zeros (first verify run: zscore 4.5e8 vs median 0.0).
        if value > 1e-6:
            get_step_anomalies().observe(
                self.program_class, value, who=self.trial_id
            )
        return record
