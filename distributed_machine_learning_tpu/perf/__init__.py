"""perf/ — the performance observatory (ISSUE 15 tentpole).

Three layers, one import, all wired through the obs plane:

* **Cost-model audit** (``perf/costmodel.py``): ``compiled.
  cost_analysis()`` captured by the AOT executable cache at compile time
  (zero extra compiles; ``<key>.cost.json`` sidecars ride the cached
  artifacts across workers), cross-checked against the analytic FLOP
  model in ``ops/flops.py``, plus roofline compute/memory-bound
  classification and the one :class:`EpochPerfAccounting` MFU helper
  both trainables share.
* **Step-stream anomaly detection** (``perf/anomaly.py``): median/MAD
  robust z-scores over per-step timings — per-trial outliers in a
  sweep, per-gang-member skew in multihost, serve ``engine.step``
  flushes via the batcher's EWMA loop.  Sustained anomalies increment
  registry counters (``perf_straggler[<who>]`` names the culprit) and
  trigger a flight-recorder dump.
* **Regression sentinel** (``perf/sentinel.py`` + ``dml-tpu perf
  compare``): the checked-in ``BENCH_r*``/``MULTICHIP_r*`` rounds
  bucketed into comparability classes so a CPU-fallback capture can
  never read as a chip-era regression.

Stdlib-only at import time (no jax) — same discipline as ``obs/``.
See docs/performance.md ("Roofline & regression sentinel") and
docs/observability.md for counter -> action tables.
"""

from __future__ import annotations

from distributed_machine_learning_tpu.perf.anomaly import (
    GangSkewMonitor,
    RobustWindow,
    StepAnomalyDetector,
    get_step_anomalies,
    skew_by_member,
)
from distributed_machine_learning_tpu.perf.costmodel import (
    DEFAULT_CROSSCHECK_TOL,
    EpochPerfAccounting,
    cost_sidecar_path,
    crosscheck,
    crosscheck_program,
    device_hbm_bandwidth,
    extract_cost,
    load_program_cost,
    program_class,
    program_cost,
    record_program_cost,
    reset_cost_store,
    roofline,
)
from distributed_machine_learning_tpu.perf.sentinel import (
    DEFAULT_NOISE_BAND,
    comparability_class,
    evaluate_rounds,
    load_round,
    load_rounds,
    reference_backend,
    render_report,
)

__all__ = [
    "DEFAULT_CROSSCHECK_TOL", "DEFAULT_NOISE_BAND",
    "EpochPerfAccounting", "GangSkewMonitor", "RobustWindow",
    "StepAnomalyDetector", "comparability_class", "cost_sidecar_path",
    "crosscheck", "crosscheck_program", "device_hbm_bandwidth",
    "evaluate_rounds", "extract_cost", "get_step_anomalies",
    "load_program_cost", "load_round", "load_rounds", "program_class",
    "program_cost", "record_program_cost", "reference_backend",
    "render_report", "reset_cost_store", "roofline", "skew_by_member",
]
