"""Cross-round bench regression sentinel: honest comparisons only.

The checked-in ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` artifacts are
the repo's performance memory — and the r03–r05 era demonstrated how
they lie by juxtaposition: the TPU probe wedged, three rounds captured
CPU-fallback numbers, and the headline sequence read "6329 → 722 →
1372 trials/h, 0.8x torch" as if the framework had collapsed 0.8x when
nothing chip-comparable was ever measured.  The sentinel parses the
round artifacts, buckets them into **comparability classes** (backend +
compute dtype + metric), and only issues regression/improvement
verdicts WITHIN a class and outside a noise band:

* Rounds on the repo's **reference backend** (the backend of the most
  recent non-CPU capture — the chip era) form the comparable chains the
  CI gate judges.
* Rounds on a *different* backend than the reference are flagged
  ``cpu_fallback`` / non-comparable: they get an informational
  same-backend delta against the previous same-class round, never a
  regression verdict against the chip chain.
* Unparseable rounds (wedged captures, ``parsed: null``) are listed,
  not guessed at.

``dml-tpu perf compare --artifacts BENCH_r*.json`` renders the report
and exits nonzero exactly when an in-class regression beyond the noise
band exists — the CI smoke gate (``.github/workflows/lint.yml``).

Stdlib-only; runs on hosts with no jax at all.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional

ROUND_RE = re.compile(r"(BENCH|MULTICHIP)_r(\d+)\.json$")

DEFAULT_NOISE_BAND = 0.15


def load_round(path: str) -> Optional[Dict[str, Any]]:
    """One artifact file -> a round record, or None for non-round paths.

    Bench rounds: ``{"kind": "bench", "round": n, "parsed": {...}|None}``.
    Multichip rounds carry health only (``ok``/``rc``/``n_devices``)."""
    m = ROUND_RE.search(os.path.basename(path))
    if not m:
        return None
    kind = m.group(1).lower()
    rec: Dict[str, Any] = {
        "path": path,
        "kind": kind,
        "round": int(m.group(2)),
    }
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        rec["error"] = str(exc)
        return rec
    if kind == "bench":
        parsed = data.get("parsed")
        rec["parsed"] = parsed if isinstance(parsed, dict) else None
    else:
        rec.update({
            "ok": bool(data.get("ok")),
            "rc": data.get("rc"),
            "n_devices": data.get("n_devices"),
            "skipped": bool(data.get("skipped")),
        })
    return rec


def load_rounds(paths: List[str]) -> List[Dict[str, Any]]:
    out = []
    for p in paths:
        rec = load_round(p)
        if rec is not None:
            out.append(rec)
    out.sort(key=lambda r: (r["kind"], r["round"]))
    return out


def comparability_class(parsed: Dict[str, Any]) -> str:
    """``<backend>+<compute_dtype>`` for one parsed bench line.  Rounds
    predating the ``compute_dtype`` field report ``?`` — the chain
    matcher treats ``?`` as compatible with any dtype on the same
    backend (r02's chip capture must anchor the chip chain, not be
    orphaned by a missing field)."""
    backend = str(parsed.get("backend") or "?")
    dtype = str(parsed.get("compute_dtype") or "?")
    return f"{backend}+{dtype}"


def _same_class(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    if (a.get("backend") or "?") != (b.get("backend") or "?"):
        return False
    da = str(a.get("compute_dtype") or "?")
    db = str(b.get("compute_dtype") or "?")
    return "?" in (da, db) or da == db


def reference_backend(rounds: List[Dict[str, Any]]) -> Optional[str]:
    """The backend perf claims are judged on: the most recent parseable
    non-CPU capture's backend — or, when every round is CPU but one
    carries a banked ``last_tpu_capture`` block, ``tpu`` (the banked
    chip evidence proves the product surface is the chip).  None when
    nothing establishes a reference (all-CPU repo: CPU is then judged
    as the reference by the caller)."""
    ref = None
    for rec in rounds:
        parsed = rec.get("parsed")
        if not parsed:
            continue
        if (parsed.get("backend") or "cpu") != "cpu":
            ref = parsed["backend"]
        elif parsed.get("last_tpu_capture") and ref is None:
            ref = "tpu"
    return ref


def evaluate_rounds(
    rounds: List[Dict[str, Any]],
    noise_band: float = DEFAULT_NOISE_BAND,
) -> Dict[str, Any]:
    """The sentinel verdict over a set of round records."""
    bench = [r for r in rounds if r["kind"] == "bench"]
    multichip = [r for r in rounds if r["kind"] == "multichip"]
    ref = reference_backend(bench)

    annotated: List[Dict[str, Any]] = []
    unparsed: List[int] = []
    for rec in bench:
        parsed = rec.get("parsed")
        if not parsed or parsed.get("value") is None:
            unparsed.append(rec["round"])
            continue
        backend = str(parsed.get("backend") or "?")
        fallback = ref is not None and backend != ref
        annotated.append({
            "round": rec["round"],
            "value": float(parsed["value"]),
            "unit": parsed.get("unit"),
            "metric": parsed.get("metric"),
            "backend": backend,
            "compute_dtype": parsed.get("compute_dtype"),
            "class": comparability_class(parsed),
            "cpu_fallback": fallback,
            "comparability": (
                f"{backend}-fallback vs {ref} (non-comparable)"
                if fallback else f"comparable ({backend} era)"
            ),
            "parsed": parsed,
        })

    # Reference chain: successive reference-backend rounds, same class.
    chain = [a for a in annotated if not a["cpu_fallback"]]
    verdicts: List[Dict[str, Any]] = []
    regressions: List[Dict[str, Any]] = []
    for prev, cur in zip(chain, chain[1:]):
        if not _same_class(prev["parsed"], cur["parsed"]):
            verdicts.append({
                "from_round": prev["round"], "to_round": cur["round"],
                "verdict": "non-comparable",
                "reason": f"{prev['class']} -> {cur['class']}",
            })
            continue
        ratio = cur["value"] / prev["value"] if prev["value"] else None
        if ratio is None:
            verdict = "non-comparable"
        elif ratio < 1.0 - noise_band:
            verdict = "regression"
        elif ratio > 1.0 + noise_band:
            verdict = "improvement"
        else:
            verdict = "flat"
        v = {
            "from_round": prev["round"], "to_round": cur["round"],
            "class": cur["class"],
            "ratio": round(ratio, 4) if ratio is not None else None,
            "noise_band": noise_band,
            "verdict": verdict,
        }
        verdicts.append(v)
        if verdict == "regression":
            regressions.append(v)

    # Fallback rounds: informational same-backend deltas only — never a
    # verdict against the reference chain (the r02->r03 "0.8x" trap).
    fallback_rounds: List[Dict[str, Any]] = []
    prev_fb: Optional[Dict[str, Any]] = None
    for a in annotated:
        if not a["cpu_fallback"]:
            continue
        entry = {
            "round": a["round"],
            "value": a["value"],
            "backend": a["backend"],
            "class": a["class"],
            "comparability": a["comparability"],
        }
        if prev_fb is not None and _same_class(
            prev_fb["parsed"], a["parsed"]
        ) and prev_fb["value"]:
            entry["vs_prev_same_backend"] = round(
                a["value"] / prev_fb["value"], 4
            )
        fallback_rounds.append(entry)
        prev_fb = a

    # Maximal runs of mutually comparable reference-backend rounds.
    chains: List[Dict[str, Any]] = []
    run: List[Dict[str, Any]] = []
    for a in chain:
        if run and not _same_class(run[-1]["parsed"], a["parsed"]):
            chains.append(run)
            run = []
        run.append(a)
    if run:
        chains.append(run)
    chains = [
        {
            "class": c[0]["class"],
            "backend": c[0]["backend"],
            "rounds": [a["round"] for a in c],
            "values": [a["value"] for a in c],
        }
        for c in chains
    ]
    return {
        "reference_backend": ref,
        "noise_band": noise_band,
        "comparable_chains": chains,
        "verdicts": verdicts,
        "regressions": regressions,
        "fallback_rounds": fallback_rounds,
        "unparsed_rounds": unparsed,
        "multichip": [
            {k: r.get(k) for k in ("round", "ok", "rc", "skipped")}
            for r in multichip
        ],
        "ok": not regressions,
    }


def render_report(report: Dict[str, Any]) -> str:
    """Human-readable sentinel verdict."""
    lines = [
        f"perf sentinel: reference backend = "
        f"{report['reference_backend'] or 'none established'}, "
        f"noise band = +/-{report['noise_band'] * 100:.0f}%"
    ]
    for c in report["comparable_chains"]:
        pts = ", ".join(
            f"r{r:02d}={v:g}" for r, v in zip(c["rounds"], c["values"])
        )
        lines.append(f"  chain [{c['class']}]: {pts}")
    if not report["comparable_chains"]:
        lines.append("  no comparable chain (no reference-backend rounds)")
    for v in report["verdicts"]:
        ratio = f" {v['ratio']:.2f}x" if v.get("ratio") is not None else ""
        lines.append(
            f"  r{v['from_round']:02d} -> r{v['to_round']:02d}:"
            f"{ratio} {v['verdict'].upper()}"
        )
    for fb in report["fallback_rounds"]:
        same = fb.get("vs_prev_same_backend")
        extra = f", {same:.2f}x vs prev same-backend" if same else ""
        lines.append(
            f"  r{fb['round']:02d}: {fb['comparability']}"
            f" (value {fb['value']:g}{extra})"
        )
    if report["unparsed_rounds"]:
        lines.append(
            "  unparsed rounds: "
            + ", ".join(f"r{r:02d}" for r in report["unparsed_rounds"])
        )
    if report["multichip"]:
        health = ", ".join(
            "r{:02d}={}".format(
                m["round"], "ok" if m["ok"] else f"rc={m['rc']}"
            )
            for m in report["multichip"]
        )
        lines.append(f"  multichip health: {health}")
    lines.append(
        "  verdict: "
        + ("OK — no in-class regression" if report["ok"] else
           f"{len(report['regressions'])} in-class regression(s) beyond "
           f"the noise band")
    )
    return "\n".join(lines)
