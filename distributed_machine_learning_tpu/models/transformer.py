"""Transformer regressors (flax.linen).

* ``TransformerRegressor`` — the flagship configurable model, capability parity
  with the reference's `TransformerModel`
  (`/root/reference/ray-tune-hpo-regression.py:183-240`): input projection,
  sin/cos positional encoding, N custom encoder layers, last-token pooling, and
  the 5-layer ReLU MLP regression head (128-64-32-16-1).  The reference's dead
  search-space knobs (`shared_weights`, `stochastic_depth_rate`,
  `key_dim_scaling` — SURVEY.md §2 C17/C19) are implemented for real:
  ``shared_weights`` runs ONE parameter set through ``nn.scan`` (ALBERT-style),
  which also gives XLA a rolled loop (one layer compiled once) instead of N
  unrolled layers — faster compiles, the key cost in HPO sweeps.
* ``SimpleTransformerRegressor`` — smoke-test model, parity with
  `SimpleTransformerModel` (`-sample.py:60-83`).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import Mesh

from distributed_machine_learning_tpu.models.layers import (
    EncoderLayer,
    PositionalEncoding,
    resolve_remat_policy,
)


class _ScanEncoderBody(nn.Module):
    """nn.scan body adapter: EncoderLayer as a (carry, _) -> (carry, None) step."""

    layer_kwargs: dict

    @nn.compact
    def __call__(self, carry, deterministic: bool = True):
        out = EncoderLayer(name="layer", **self.layer_kwargs)(
            carry, deterministic=deterministic
        )
        return out, None


class RegressionHead(nn.Module):
    """ReLU MLP head; default widths match the reference's fc1..fc5 (`:217-221`)."""

    hidden_sizes: Sequence[int] = (128, 64, 32, 16)
    out_features: int = 1
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for width in self.hidden_sizes:
            x = nn.relu(nn.Dense(width, dtype=self.dtype)(x))
        return nn.Dense(self.out_features, dtype=self.dtype)(x)


class TransformerRegressor(nn.Module):
    d_model: int = 64
    num_heads: int = 4
    num_layers: int = 2
    dim_feedforward: int = 128
    dropout_rate: float = 0.1
    attention_type: str = "scaled_dot_product"
    key_dim_scaling: float = 0.5
    depthwise_separable_conv: bool = False
    attn_kernel_size: int = 3
    stochastic_depth_rate: float = 0.0
    # Feed-forward family: "linear" | "depthwise_separable" | "moe" (None =
    # legacy depthwise_separable_conv bool). "moe" makes every block's FF a
    # top-k routed expert mixture (models/moe.py) whose stacked expert
    # params shard over the 'ep' mesh axis.
    feedforward_type: Optional[str] = None
    num_experts: int = 8
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    moe_aux_coef: float = 1e-2
    shared_weights: bool = False
    max_seq_length: int = 2000
    head_hidden_sizes: Sequence[int] = (128, 64, 32, 16)
    out_features: int = 1
    # Long-context sequence parallelism: with a mesh + seq_axis, every
    # attention block runs sequence-sharded over that mesh axis while the
    # rest of the model stays under GSPMD — sequence length then scales with
    # the mesh, not per-chip HBM. seq_parallel_mode picks "ring"
    # (parallel/ring_attention.py) or "ulysses" (parallel/ulysses.py).
    seq_axis: Optional[str] = None
    seq_parallel_mode: str = "ring"
    batch_axis: Optional[str] = "dp"
    head_axis: Optional[str] = "tp"
    mesh: Optional[Mesh] = None
    # Mixed precision: compute dtype for every matmul/conv in the model
    # (params stay float32; losses and attention softmax stay float32).
    # jnp.bfloat16 doubles MXU throughput and halves activation HBM traffic
    # on TPU. Wired from config["compute_dtype"] by models.build_model.
    dtype: Optional[jnp.dtype] = None
    # Position information: "sincos" (the reference's additive table,
    # fixed and capped at max_seq_length), "rope" (rotary embedding on
    # q/k inside every attention block — relative positions, no length
    # cap, the long-context default), or "none".
    position_encoding: str = "sincos"
    # Grouped-query attention: kv heads per block (None = num_heads; 1 =
    # multi-query). See models/layers.py MultiHeadAttention.
    num_kv_heads: Optional[int] = None
    # Attention tile override (flash block_q/block_k) — None = the
    # kernel's measured-fastest defaults; bench.py's flagship tile probe
    # sets it from config["block_size"].
    block_size: Optional[int] = None
    # Rematerialization (jax.checkpoint): drop each encoder block's
    # activations in the forward and recompute them in the backward —
    # activation memory goes from O(num_layers) to O(1) blocks at ~1/3
    # extra FLOPs. The knob that fits long-context/big-batch configs into
    # HBM; numerics are identical (tested).
    remat: bool = False
    # Remat POLICY (jax.checkpoint_policies name, e.g. "dots_saveable"):
    # with remat on, selects which intermediates each block may keep —
    # "dots_saveable" keeps matmul outputs (recompute only the cheap
    # elementwise ops), "nothing_saveable" is the full-recompute default.
    # The HBM-vs-FLOPs dial for the sharded flagship (config key
    # "remat_policy"; docs/performance.md).
    remat_policy: Optional[str] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        """x: [batch, seq, input_features] -> [batch, out_features].

        input_size is derived from the data (x.shape[-1]) instead of the
        reference's hard-coded ``input_size=10`` (`:271` vs its 81-column
        pipeline — SURVEY.md §3.3 note).
        """
        if self.position_encoding not in ("sincos", "rope", "none"):
            raise ValueError(
                f"Unknown position_encoding {self.position_encoding!r}; "
                f"expected 'sincos', 'rope', or 'none'"
            )
        layer_kwargs = dict(
            dtype=self.dtype,
            rope=self.position_encoding == "rope",
            num_kv_heads=self.num_kv_heads,
            block_size=self.block_size,
            d_model=self.d_model,
            num_heads=self.num_heads,
            dim_feedforward=self.dim_feedforward,
            dropout_rate=self.dropout_rate,
            attention_type=self.attention_type,
            key_dim_scaling=self.key_dim_scaling,
            depthwise_separable_conv=self.depthwise_separable_conv,
            attn_kernel_size=self.attn_kernel_size,
            stochastic_depth_rate=self.stochastic_depth_rate,
            feedforward_type=self.feedforward_type,
            num_experts=self.num_experts,
            expert_top_k=self.expert_top_k,
            capacity_factor=self.capacity_factor,
            moe_aux_coef=self.moe_aux_coef,
            seq_axis=self.seq_axis,
            seq_parallel_mode=self.seq_parallel_mode,
            batch_axis=self.batch_axis,
            head_axis=self.head_axis,
            mesh=self.mesh,
        )

        x = nn.Dense(self.d_model, name="input_projection", dtype=self.dtype)(x)
        if self.position_encoding == "sincos":
            x = PositionalEncoding(
                d_model=self.d_model,
                dropout_rate=self.dropout_rate,
                max_len=self.max_seq_length,
            )(x, deterministic=deterministic)
        else:
            # Keep the input-dropout regularization the sincos path applies.
            x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)

        # nn.remat wraps the MODULE CLASS: each block's forward re-runs
        # inside the backward instead of keeping its activations live.
        # deterministic is argnum 2 (self counts) and must be STATIC —
        # Dropout branches on it in Python, which a traced bool would break.
        remat_kwargs = dict(static_argnums=(2,))
        if self.remat and self.remat_policy:
            remat_kwargs["policy"] = resolve_remat_policy(self.remat_policy)
        if self.shared_weights:
            # ALBERT-style: one EncoderLayer parameter set applied num_layers
            # times, rolled with nn.scan so XLA compiles the body once.
            body = (
                nn.remat(_ScanEncoderBody, **remat_kwargs)
                if self.remat else _ScanEncoderBody
            )
            ScanLayer = nn.scan(
                body,
                variable_broadcast="params",
                # Sown per-layer values (e.g. the MoE aux loss) stack along
                # the scan dimension instead of erroring inside nn.scan.
                variable_axes={"moe": 0},
                split_rngs={"params": False, "dropout": True},
                length=self.num_layers,
                in_axes=(nn.broadcast,),
            )
            x, _ = ScanLayer(layer_kwargs=layer_kwargs, name="shared_layer")(
                x, deterministic
            )
        else:
            Layer = (
                nn.remat(EncoderLayer, **remat_kwargs)
                if self.remat else EncoderLayer
            )
            for i in range(self.num_layers):
                # Positional: jax.checkpoint's static_argnums cover
                # positionals only.
                x = Layer(name=f"layer_{i}", **layer_kwargs)(
                    x, deterministic
                )

        x = x[:, -1, :]  # last-token pooling (`:235`)
        return RegressionHead(
            hidden_sizes=tuple(self.head_hidden_sizes),
            out_features=self.out_features,
            dtype=self.dtype,
            name="head",
        )(x)


class SimpleTransformerRegressor(nn.Module):
    """Smoke-test model: stock encoder stack + last-token + single Linear head.

    Parity: `SimpleTransformerModel` (`-sample.py:60-83`).
    """

    d_model: int = 64
    num_heads: int = 4
    num_layers: int = 2
    dim_feedforward: int = 256
    dropout_rate: float = 0.1
    max_seq_length: int = 2000
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        x = nn.Dense(self.d_model, name="input_projection", dtype=self.dtype)(x)
        x = PositionalEncoding(
            d_model=self.d_model,
            dropout_rate=self.dropout_rate,
            max_len=self.max_seq_length,
        )(x, deterministic=deterministic)
        for i in range(self.num_layers):
            x = EncoderLayer(
                d_model=self.d_model,
                num_heads=self.num_heads,
                dim_feedforward=self.dim_feedforward,
                dropout_rate=self.dropout_rate,
                dtype=self.dtype,
                name=f"layer_{i}",
            )(x, deterministic=deterministic)
        return nn.Dense(1, name="head", dtype=self.dtype)(x[:, -1, :])
