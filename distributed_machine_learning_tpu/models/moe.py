"""Mixture-of-Experts feed-forward with expert parallelism.

Beyond-parity capability (the reference has no MoE — SURVEY.md §2c lists
expert parallelism as absent): a sparsely-activated feed-forward block that
scales parameter count without scaling per-token FLOPs, designed the TPU way.

Design (GShard/Switch einsum formulation, the shape that maps onto the MXU
and GSPMD):

* Experts live as ONE stacked parameter tensor ``w_in [E, d_model, d_ff]`` /
  ``w_out [E, d_ff, d_model]``, sharded over the ``ep`` mesh axis
  (`parallel/sharding.py` rules).  There is no per-expert Python loop —
  expert compute is a single batched einsum over the E dimension, which XLA
  partitions across the mesh; token dispatch/combine einsums become
  all-to-all-style collectives on ICI automatically.
* Tokens are routed within fixed-size **groups** (GShard's trick): the
  dispatch/combine one-hot tensors are ``[G, group, E, capacity]`` with
  ``capacity ~ k*group/E``, so routing memory grows linearly with token
  count (``O(T * group * k)``) instead of quadratically — long sequences
  and big batches stay affordable.
* Routing math is dense and static-shaped under jit: top-k gating over
  router logits, position-in-expert via per-group cumulative sums, fixed
  per-group capacity.  Tokens over capacity are dropped (their FF
  contribution is zero; the encoder block's residual path still carries
  them) — the standard Switch trade for static shapes.
* The load-balance auxiliary loss (mean expert load x mean router prob,
  scaled by E, Switch-style) is sown into the ``"moe"`` collection already
  multiplied by ``aux_loss_coef``; the training loops add any sown values
  straight onto the objective (`tune/_regression_program.py`,
  `parallel/train_step.py`).
* Router math runs in float32 even under a bfloat16 compute dtype — gating
  is precision-sensitive, the rest of the block follows the input dtype.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp


def expert_capacity(capacity_factor: float, top_k: int, group: int,
                    num_experts: int) -> int:
    """Static per-expert slot count per routing group.

    Ceil of ``capacity_factor * top_k * group / num_experts`` (the
    GShard/Switch convention), floored at 1 slot so every expert is
    addressable even in degenerate tiny-group configs.
    """
    return max(math.ceil(capacity_factor * top_k * group / num_experts), 1)


def collect_aux(mutated_collections) -> jnp.ndarray:
    """Sum every aux term sown into the ``"moe"`` collection of a
    ``model.apply(..., mutable=["moe"])`` result — THE way training loops
    fold the load-balance loss into their objective (keeps the two train
    paths, tune/_regression_program.py and parallel/train_step.py, in
    lockstep)."""
    leaves = jax.tree_util.tree_leaves(mutated_collections.get("moe", {}))
    if not leaves:
        return jnp.float32(0.0)
    return sum(jnp.sum(leaf) for leaf in leaves)


class MoEFF(nn.Module):
    """Top-k routed mixture-of-experts feed-forward (relu MLP experts)."""

    d_model: int
    dim_feedforward: int
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 1e-2
    # Routing-group size in tokens (GShard "G" dimension). Memory for the
    # dispatch tensors is T/group * group^2 * k — keep groups ~1k tokens.
    group_size: int = 1024

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.top_k > self.num_experts:
            raise ValueError(
                f"top_k={self.top_k} > num_experts={self.num_experts}"
            )
        B, S, D = x.shape
        E, K = self.num_experts, self.top_k
        F = self.dim_feedforward
        T = B * S
        # Largest divisor of T at most group_size, so grouping is exact with
        # static shapes (same trick as blockwise attention's block size).
        g = min(self.group_size, T)
        while T % g:
            g -= 1
        G = T // g
        # Static per-expert capacity per group, with headroom for imbalance.
        capacity = expert_capacity(self.capacity_factor, K, g, E)

        # batch_axis=0: the expert dim is a batch of independent MLPs, not
        # receptive field — without it variance_scaling counts fan_in = E*D
        # and every expert starts sqrt(E) under-scaled.
        expert_init = nn.initializers.lecun_normal(batch_axis=0)
        w_in = self.param("w_in", expert_init, (E, D, F), jnp.float32)
        b_in = self.param("b_in", nn.initializers.zeros, (E, F), jnp.float32)
        w_out = self.param("w_out", expert_init, (E, F, D), jnp.float32)
        b_out = self.param("b_out", nn.initializers.zeros, (E, D), jnp.float32)

        toks = x.reshape(G, g, D)

        # -- routing (float32) ------------------------------------------------
        logits = nn.Dense(E, name="router", dtype=jnp.float32)(
            toks.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)                  # [G, g, E]
        gate_vals, expert_idx = jax.lax.top_k(probs, K)          # [G, g, K]
        gate_vals = gate_vals / (
            jnp.sum(gate_vals, axis=-1, keepdims=True) + 1e-9
        )

        # Position-in-expert, slot by slot: slot j's tokens queue behind all
        # of slot j-1's tokens for the same expert (GShard ordering), within
        # each group independently.
        sel_onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [G,g,K,E]
        base = jnp.zeros((G, E), jnp.float32)
        dispatch = jnp.zeros((G, g, E, capacity), x.dtype)
        combine = jnp.zeros((G, g, E, capacity), x.dtype)
        for j in range(K):
            mask_j = sel_onehot[:, :, j, :]                       # [G, g, E]
            pos_j = jnp.cumsum(mask_j, axis=1) - 1.0 + base[:, None, :]
            keep_j = mask_j * (pos_j < capacity)
            pos_onehot = jax.nn.one_hot(
                jnp.where(keep_j > 0, pos_j, -1.0)
                .max(axis=-1)
                .astype(jnp.int32),
                capacity,
                dtype=jnp.float32,
            )                                                     # [G, g, C]
            disp_j = keep_j[..., None] * pos_onehot[:, :, None, :]  # [G,g,E,C]
            dispatch = dispatch + disp_j.astype(x.dtype)
            combine = combine + (
                disp_j * gate_vals[:, :, j, None, None]
            ).astype(x.dtype)
            base = base + mask_j.sum(axis=1)

        # -- expert compute (batched over G and E; ep-sharded under GSPMD) ----
        expert_in = jnp.einsum("gtec,gtd->gecd", dispatch, toks)  # [G, E, C, D]
        h = nn.relu(
            jnp.einsum("gecd,edf->gecf", expert_in, w_in.astype(x.dtype))
            + b_in[None, :, None, :].astype(x.dtype)
        )
        expert_out = (
            jnp.einsum("gecf,efd->gecd", h, w_out.astype(x.dtype))
            + b_out[None, :, None, :].astype(x.dtype)
        )
        y = jnp.einsum("gtec,gecd->gtd", combine, expert_out)     # [G, g, D]

        # -- load-balance aux loss (Switch eq. 4): E * sum_e f_e * P_e --------
        top1 = jax.nn.one_hot(expert_idx[..., 0], E, dtype=jnp.float32)
        load_frac = top1.mean(axis=(0, 1))   # fraction routed (top-1) per expert
        prob_frac = probs.mean(axis=(0, 1))  # mean router prob per expert
        aux = self.aux_loss_coef * E * jnp.sum(load_frac * prob_frac)
        self.sow("moe", "aux_loss", aux)

        return y.reshape(B, S, D)
