"""Per-model-family partition-rule tables.

Supersedes the era when ``TRANSFORMER_TP_RULES`` was the ONLY spec table:
every model family registers its own rule list here (first match wins,
``re.search`` semantics — ``parallel/partition.py``; the transformer entry
re-exports the canonical table from ``parallel/sharding.py``, whose layer
owns no model imports), and the sharded trainable / bench / ckpt surfaces
resolve the table from the trial config via :func:`rules_for`.  Adding a
family = registering a table, not editing the trainable.

Rule anatomy (docs/performance.md "Partition rules, donation, and remat"):
shard the two big matmuls of each block column-then-row over ``tp`` so one
reduce per block suffices; shard MoE expert stacks over ``ep``; replicate
everything small (norms, biases that would cut against their dim,
routers).  Specs are intent — ``partition.clean_spec`` drops axes the
actual mesh/leaf cannot honor, so one table serves every mesh shape.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from jax.sharding import PartitionSpec as P

from distributed_machine_learning_tpu.parallel.partition import (
    RuleList,
    rules_fingerprint,
)
from distributed_machine_learning_tpu.parallel.sharding import (
    TRANSFORMER_TP_RULES,
)

TRANSFORMER_RULES = TRANSFORMER_TP_RULES

# MLP: column/row-alternate the big Dense kernels.  Written in the
# TUPLE-PATH dialect (component regexes) — exercising the second rule
# dialect on a real table keeps the parity golden tests honest.
MLP_RULES: Tuple = (
    (("Dense_0", "kernel"), P(None, "tp")),
    (("Dense_1", "kernel"), P("tp", None)),
    ((r"Dense_\d+", "bias"), P()),
    (r".*", P()),
)

# Conv families: channel dims are small relative to tp on realistic
# meshes; replicate (dp carries the parallelism).  Dense heads column-
# shard where divisible.
CNN_RULES: Tuple = (
    (r"Dense_0/kernel$", P(None, "tp")),
    (r".*", P()),
)

RNN_RULES: Tuple = (
    (r".*", P()),
)

RESNET_RULES: Tuple = (
    (r".*", P()),
)

# family name (models.build_model's config["model"]) -> rule table
PARTITION_RULE_TABLES: Dict[str, RuleList] = {
    "transformer": TRANSFORMER_RULES,
    "simple_transformer": TRANSFORMER_RULES,
    "mlp": MLP_RULES,
    "cnn1d": CNN_RULES,
    "rnn": RNN_RULES,
    "resnet18": RESNET_RULES,
}

DEFAULT_RULES: RuleList = ((r".*", P()),)


def register_partition_rules(family: str, rules: RuleList) -> None:
    """Register (or replace) a family's rule table."""
    PARTITION_RULE_TABLES[str(family)] = tuple(rules)


def rules_for(config: Dict[str, Any]) -> RuleList:
    """The rule table a trial config's model family shards under.

    ``config["partition_rules"]`` overrides per trial (a list of
    ``(pattern, spec-as-list)`` pairs is accepted for JSON-carried
    configs); otherwise the family registry decides, falling back to
    replicate-everything for unknown families.
    """
    override = config.get("partition_rules")
    if override is not None:
        from distributed_machine_learning_tpu.parallel.partition import (
            spec_from_jsonable,
        )

        out = []
        for pattern, spec in override:
            if not isinstance(spec, P):
                spec = spec_from_jsonable(spec)
            out.append((pattern, spec))
        return tuple(out)
    family = str(config.get("model", "transformer"))
    return PARTITION_RULE_TABLES.get(family, DEFAULT_RULES)


def rules_fingerprint_for(config: Dict[str, Any]) -> str:
    """Stable fingerprint of the table :func:`rules_for` resolves —
    compile-key material (``compilecache.keys.sharded_program_key``)."""
    return rules_fingerprint(rules_for(config))
