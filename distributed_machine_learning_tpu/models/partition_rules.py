"""Per-model-family partition-rule tables.

Supersedes the era when ``TRANSFORMER_TP_RULES`` was the ONLY spec table:
every model family registers its own rule list here (first match wins,
``re.search`` semantics — ``parallel/partition.py``; the transformer entry
re-exports the canonical table from ``parallel/sharding.py``, whose layer
owns no model imports), and the sharded trainable / bench / ckpt surfaces
resolve the table from the trial config via :func:`rules_for`.  Adding a
family = registering a table, not editing the trainable.

Rule anatomy (docs/performance.md "Partition rules, donation, and remat"):
shard the two big matmuls of each block column-then-row over ``tp`` so one
reduce per block suffices; shard MoE expert stacks over ``ep``; replicate
everything small (norms, biases that would cut against their dim,
routers).  Specs are intent — ``partition.clean_spec`` drops axes the
actual mesh/leaf cannot honor, so one table serves every mesh shape.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

from jax.sharding import PartitionSpec as P

from distributed_machine_learning_tpu.parallel.partition import (
    RuleList,
    rules_fingerprint,
)
from distributed_machine_learning_tpu.parallel.sharding import (
    TRANSFORMER_TP_RULES,
)

TRANSFORMER_RULES = TRANSFORMER_TP_RULES

# MLP: column/row-alternate the big Dense kernels.  Written in the
# TUPLE-PATH dialect (component regexes) — exercising the second rule
# dialect on a real table keeps the parity golden tests honest.
MLP_RULES: Tuple = (
    (("Dense_0", "kernel"), P(None, "tp")),
    (("Dense_1", "kernel"), P("tp", None)),
    ((r"Dense_\d+", "bias"), P()),
    (r".*", P()),
)

# Conv families (ROADMAP item 1 remainder): a Conv1d kernel is
# (window, in_ch, out_ch) — out-channel is the reduction-free dim, so
# column-shard it over tp (each shard computes its own channel slice; no
# collective until a later row-sharded matmul reduces).  The Dense head
# pair then alternates column-then-row like every other family, so one
# reduce per head suffices.  Biases/norms replicate (cutting a bias
# against its only dim buys nothing); clean_spec drops the tp axis
# per-leaf where a channel count does not divide the mesh.
CNN_RULES: Tuple = (
    (r"Conv_\d+/kernel$", P(None, None, "tp")),
    (r"Dense_0/kernel$", P(None, "tp")),
    (r"Dense_1/kernel$", P("tp", None)),
    (r".*", P()),
)

# Recurrent families: every LSTM/GRU gate is a Dense producing the hidden
# dim — input kernels (i\w: ii/if/ig/io, ir/iz/in) are (features, hidden)
# and recurrent kernels (h\w: hi/hf/hg/ho, hr/hz/hn) are (hidden, hidden);
# column-shard both over tp so each shard owns a hidden-slice of every
# gate and the scan's per-step matmuls stay local.  The MLP head then
# alternates column (head_*) / row (out) to close with one reduce.
RNN_RULES: Tuple = (
    (r"(lstm|gru)_\d+/i[a-z]{1,2}/kernel$", P(None, "tp")),
    (r"(lstm|gru)_\d+/h[a-z]{1,2}/kernel$", P(None, "tp")),
    (r"head_\d+/kernel$", P(None, "tp")),
    (r"out/kernel$", P("tp", None)),
    (r".*", P()),
)

# ResNet was replicate-only until the jaxlint coverage audit (DML101)
# priced it: the stage-2/3 conv stacks are ~80% of the family's params and
# every kernel was silently falling through to the catch-all.  Same
# recipe as CNN_RULES, one rank up: a 2-D conv kernel is
# (kh, kw, in_ch, out_ch) — column-shard the reduction-free out-channel
# dim over tp (64..512 all divide the tier-1 tp sizes); the (1, 1, in,
# out) projection shortcuts follow.  The Dense head (512, 1) replicates
# by explicit rule: its out dim is 1, there is nothing to shard.
RESNET_RULES: Tuple = (
    (r"(stem|Conv_\d+|proj)/kernel$", P(None, None, None, "tp")),
    (r"head/kernel$", P()),
    (r".*", P()),
)

# family name (models.build_model's config["model"]) -> rule table
PARTITION_RULE_TABLES: Dict[str, RuleList] = {
    "transformer": TRANSFORMER_RULES,
    "simple_transformer": TRANSFORMER_RULES,
    "mlp": MLP_RULES,
    "cnn1d": CNN_RULES,
    "rnn": RNN_RULES,
    "resnet18": RESNET_RULES,
}

DEFAULT_RULES: RuleList = ((r".*", P()),)


def register_partition_rules(family: str, rules: RuleList) -> None:
    """Register (or replace) a family's rule table."""
    PARTITION_RULE_TABLES[str(family)] = tuple(rules)


def rules_for(config: Dict[str, Any]) -> RuleList:
    """The rule table a trial config's model family shards under.

    ``config["partition_rules"]`` overrides per trial (a list of
    ``(pattern, spec-as-list)`` pairs is accepted for JSON-carried
    configs); otherwise the family registry decides, falling back to
    replicate-everything for unknown families.
    """
    override = config.get("partition_rules")
    if override is not None:
        from distributed_machine_learning_tpu.parallel.partition import (
            spec_from_jsonable,
        )

        out = []
        for pattern, spec in override:
            if not isinstance(spec, P):
                spec = spec_from_jsonable(spec)
            out.append((pattern, spec))
        return tuple(out)
    family = str(config.get("model", "transformer"))
    return PARTITION_RULE_TABLES.get(family, DEFAULT_RULES)


def rules_fingerprint_for(config: Dict[str, Any]) -> str:
    """Stable fingerprint of the table :func:`rules_for` resolves —
    compile-key material (``compilecache.keys.sharded_program_key``)."""
    return rules_fingerprint(rules_for(config))
