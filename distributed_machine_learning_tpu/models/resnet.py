"""ResNet regression models (BASELINE.json config 5: ResNet-18 regression head).

Standard pre-activation-free ResNet-v1 basic blocks in flax.  BatchNorm state is
carried as a ``batch_stats`` collection; the trainable plumbs it through the
train step (see ``tune.trainable``).  Works on [B, H, W, C] images; a 1-D
variant wraps time-series inputs as [B, S, 1, C].
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    # Compute dtype for convs (params stay float32). BatchNorm gets it too;
    # its batch statistics are still accumulated in float32 internally
    # (flax upcasts for mean/var), only the normalized output is narrow.
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype)
        residual = x
        y = nn.Conv(self.features, (3, 3), strides=(self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features, (1, 1),
                               strides=(self.strides, self.strides),
                               use_bias=False, dtype=self.dtype,
                               name="proj")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNetRegressor(nn.Module):
    """ResNet-v1 with a regression head. stage_sizes=(2,2,2,2) == ResNet-18."""

    stage_sizes: Sequence[int] = (2, 2, 2, 2)
    width: int = 64
    out_features: int = 1
    dtype: Optional[jnp.dtype] = None  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        if x.ndim == 3:  # [B, S, F] time series -> pseudo-image [B, S, 1, F]
            x = x[:, :, None, :]
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=self.dtype, name="stem")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if (i > 0 and j == 0) else 1
                x = BasicBlock(self.width * (2 ** i), strides=strides,
                               dtype=self.dtype,
                               name=f"stage{i}_block{j}")(x, train=train)
        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(self.out_features, dtype=self.dtype, name="head")(x)


def ResNet18Regressor(out_features: int = 1,
                      dtype: Optional[jnp.dtype] = None) -> ResNetRegressor:
    return ResNetRegressor(stage_sizes=(2, 2, 2, 2), out_features=out_features,
                           dtype=dtype)
