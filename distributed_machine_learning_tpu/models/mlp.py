"""MLP regressor — the seed model for HPO sweeps (BASELINE.json config 1/2).

Flattens sequence inputs if present, so it is drop-in on both tabular
(California Housing) and windowed time-series batches.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLPRegressor(nn.Module):
    hidden_sizes: Sequence[int] = (128, 64)
    dropout_rate: float = 0.0
    out_features: int = 1
    dtype: Optional[jnp.dtype] = None  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        for width in self.hidden_sizes:
            x = nn.relu(nn.Dense(int(width), dtype=self.dtype)(x))
            x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        return nn.Dense(self.out_features, dtype=self.dtype)(x)
