"""Model zoo + config->model factory.

``build_model(config)`` constructs a model from a trial config dict, deriving
architecture fields from the config keys the reference's search spaces use
(`/root/reference/ray-tune-hpo-regression.py:379-400`).
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from distributed_machine_learning_tpu.models.cnn import CNN1DRegressor
from distributed_machine_learning_tpu.models.mlp import MLPRegressor
from distributed_machine_learning_tpu.models.moe import MoEFF
from distributed_machine_learning_tpu.models.rnn import RNNRegressor
from distributed_machine_learning_tpu.models.resnet import (
    ResNet18Regressor,
    ResNetRegressor,
)
from distributed_machine_learning_tpu.models.transformer import (
    SimpleTransformerRegressor,
    TransformerRegressor,
)
from distributed_machine_learning_tpu.utils.registry import Registry

models: Registry = Registry("model")

_DTYPE_NAMES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "f32": jnp.float32,
    "bf16": jnp.bfloat16,
}


def compute_dtype_of(config: Dict[str, Any]):
    """Resolve ``config["compute_dtype"]`` to a jnp dtype (None = float32
    promotion, flax's default). One lookup shared by every family builder
    AND the train loops' input staging, so the model's matmul dtype and the
    staged data dtype can never disagree."""
    cd = config.get("compute_dtype")
    if cd is None or not isinstance(cd, str):
        return cd
    try:
        return _DTYPE_NAMES[cd]
    except KeyError:
        raise ValueError(
            f"Unknown compute_dtype {cd!r}; expected one of "
            f"{sorted(_DTYPE_NAMES)}"
        ) from None


@models.register("mlp")
def _build_mlp(config: Dict[str, Any]):
    return MLPRegressor(
        hidden_sizes=tuple(config.get("hidden_sizes", (128, 64))),
        dropout_rate=config.get("dropout", 0.0),
        out_features=config.get("out_features", 1),
        dtype=compute_dtype_of(config),
    )


@models.register("cnn1d")
def _build_cnn(config: Dict[str, Any]):
    return CNN1DRegressor(
        channels=tuple(config.get("channels", (32, 64))),
        kernel_size=config.get("kernel_size", 5),
        dropout_rate=config.get("dropout", 0.0),
        head_hidden=config.get("head_hidden", 64),
        out_features=config.get("out_features", 1),
        dtype=compute_dtype_of(config),
    )


@models.register("transformer")
def _build_transformer(config: Dict[str, Any]):
    d_model = config.get("d_model", 64)
    return TransformerRegressor(
        d_model=d_model,
        num_heads=config.get("num_heads", 4),
        num_layers=config.get("num_encoder_layers", config.get("num_layers", 2)),
        dim_feedforward=config.get("dim_feedforward", d_model * 2),
        dropout_rate=config.get("dropout", 0.1),
        attention_type=config.get("attention_type", "scaled_dot_product"),
        key_dim_scaling=config.get("key_dim_scaling", 0.5),
        depthwise_separable_conv=config.get("depthwise_separable_conv", False),
        attn_kernel_size=config.get("attn_kernel_size", 3),
        stochastic_depth_rate=config.get("stochastic_depth_rate", 0.0),
        feedforward_type=config.get("feedforward_type"),
        num_experts=config.get("num_experts", 8),
        expert_top_k=config.get("expert_top_k", 2),
        capacity_factor=config.get("capacity_factor", 1.25),
        moe_aux_coef=config.get("moe_aux_coef", 1e-2),
        shared_weights=config.get("shared_weights", False),
        max_seq_length=config.get("max_seq_length", 2000),
        out_features=config.get("out_features", 1),
        seq_axis=config.get("seq_axis"),
        seq_parallel_mode=config.get("seq_parallel_mode", "ring"),
        batch_axis=config.get("batch_axis", "dp"),
        head_axis=config.get("head_axis", "tp"),
        mesh=config.get("mesh"),
        dtype=compute_dtype_of(config),
        position_encoding=config.get("position_encoding", "sincos"),
        num_kv_heads=config.get("num_kv_heads"),
        block_size=config.get("block_size"),
        remat=config.get("remat", False),
        remat_policy=config.get("remat_policy"),
    )


@models.register("simple_transformer")
def _build_simple_transformer(config: Dict[str, Any]):
    return SimpleTransformerRegressor(
        d_model=config.get("d_model", 64),
        num_heads=config.get("num_heads", 4),
        num_layers=config.get("num_layers", 2),
        dim_feedforward=config.get("dim_feedforward", 256),
        dropout_rate=config.get("dropout", 0.1),
        max_seq_length=config.get("max_seq_length", 2000),
        dtype=compute_dtype_of(config),
    )


@models.register("resnet18")
def _build_resnet18(config: Dict[str, Any]):
    return ResNet18Regressor(
        out_features=config.get("out_features", 1),
        dtype=compute_dtype_of(config),
    )


@models.register("rnn")
def _build_rnn(config: Dict[str, Any]):
    return RNNRegressor(
        hidden_size=config.get("hidden_size", 64),
        num_layers=config.get("num_layers", 1),
        cell_type=config.get("cell_type", "lstm"),
        dropout_rate=config.get("dropout", 0.0),
        head_hidden_sizes=tuple(config.get("head_hidden_sizes", (64,))),
        out_features=config.get("out_features", 1),
        dtype=compute_dtype_of(config),
    )


def build_model(config: Dict[str, Any]):
    """Construct a model from a trial config; ``config['model']`` picks the family."""
    return models.get(config.get("model", "transformer"))(config)


__all__ = [
    "models",
    "build_model",
    "compute_dtype_of",
    "MLPRegressor",
    "MoEFF",
    "CNN1DRegressor",
    "TransformerRegressor",
    "SimpleTransformerRegressor",
    "ResNetRegressor",
    "ResNet18Regressor",
    "RNNRegressor",
]
