"""1-D CNN tabular/time-series regressor (BASELINE.json config 3, PBT workload)."""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class CNN1DRegressor(nn.Module):
    """Conv1d stack over [batch, seq, features] with global average pooling."""

    channels: Sequence[int] = (32, 64)
    kernel_size: int = 5
    dropout_rate: float = 0.0
    head_hidden: int = 64
    out_features: int = 1
    dtype: Optional[jnp.dtype] = None  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if x.ndim == 2:  # tabular -> single-step sequence
            x = x[:, None, :]
        for ch in self.channels:
            x = nn.Conv(
                int(ch), kernel_size=(self.kernel_size,), padding="SAME",
                dtype=self.dtype,
            )(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        x = x.mean(axis=1)  # global average pool over sequence
        x = nn.relu(nn.Dense(self.head_hidden, dtype=self.dtype)(x))
        return nn.Dense(self.out_features, dtype=self.dtype)(x)
