"""Building-block layers for the model zoo (flax.linen).

TPU-native re-designs of the reference's layer components, with its latent bugs
fixed and its intended-but-unimplemented knobs made real (SURVEY.md §2 C7-C10):

* ``PositionalEncoding`` — sin/cos table built at the right rank (the reference
  built a 2-D buffer and indexed it 3-D, `ray-tune-hpo-regression.py:40-43,53`).
* ``MultiHeadAttention`` — one module covering the reference's attention
  registry (`:138-145`): softmax ("scaled_dot_product" / "multi_head_attention"),
  true O(n) "linear_attention", and "blockwise" for long sequences, with a real
  ``key_dim_scaling`` exponent (C19's dead knob).
* ``DepthwiseSeparableFF`` — depthwise + pointwise conv feed-forward with an
  output projection back to d_model (the reference omitted it, so its residual
  add shape-mismatched, `:69,:176`).
* ``StochasticDepth`` — per-sample residual-branch drop (C19's dead
  ``stochastic_depth_rate`` knob, implemented).
* ``EncoderLayer`` — post-LN block matching `CustomEncoderLayer` (`:122-178`).
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_machine_learning_tpu.models.moe import MoEFF
from distributed_machine_learning_tpu.ops.attention import (
    blockwise_attention,
    dot_product_attention,
    largest_divisor_block,
    linear_attention,
)

ATTENTION_TYPES = (
    "scaled_dot_product",
    "multi_head_attention",
    "linear_attention",
    "blockwise",
    "flash",
)


def resolve_remat_policy(name):
    """A ``jax.checkpoint_policies`` policy from its config name.

    Accepted: None/""/"none" (no policy — full remat when remat is on) or
    any attribute of ``jax.checkpoint_policies`` ("dots_saveable",
    "nothing_saveable", "everything_saveable",
    "dots_with_no_batch_dims_saveable", ...).  The knob that trades
    recompute FLOPs against activation HBM per block — wired from
    ``config["remat_policy"]`` (docs/performance.md).
    """
    if name is None or name in ("", "none", False):
        return None
    policy = getattr(jax.checkpoint_policies, str(name), None)
    if policy is None:
        valid = sorted(
            n for n in dir(jax.checkpoint_policies) if not n.startswith("_")
        )
        raise ValueError(
            f"Unknown remat policy {name!r}; expected one of {valid}"
        )
    return policy


def activation_spec(mesh: Mesh, shape, *axes) -> P:
    """A per-dim mesh-axis intent cleaned against an activation's shape:
    axes the mesh lacks or whose size does not divide the dim drop to None
    (same reconciliation rule as ``parallel.partition.clean_spec``,
    duplicated here so the model zoo never imports the parallel package at
    module level)."""
    cleaned = []
    for dim, axis in zip(shape, axes):
        if (
            axis is None
            or mesh is None
            or axis not in mesh.axis_names
            or int(dim) % int(mesh.shape[axis]) != 0
        ):
            cleaned.append(None)
        else:
            cleaned.append(axis)
    return P(*cleaned)


def constrain_activation(x: jnp.ndarray, mesh: Optional[Mesh], *axes):
    """Pin an activation's layout at a block boundary (residual stream,
    attention q/k/v) with ``with_sharding_constraint``.

    Without the pin, GSPMD is free to resolve the layout from whichever
    neighboring op it propagates first — on dp×tp meshes that can
    materialize a replicated [B, S, H, D] attention intermediate or bounce
    the residual stream through an unnecessary all-gather.  No-op without
    a mesh (single-device / unsharded paths build models with mesh=None).
    """
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, activation_spec(mesh, x.shape, *axes))
    )


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backend probing never fatal
        return False


def _route_softmax_to_flash(seq_len: int, head_dim: int) -> bool:
    """Whether a plain softmax attention call should run the Pallas flash
    kernel instead: same exact math (online softmax), measured faster on
    chip from ~1k sequence length at head_dim <= 64 (benchmarks/RESULTS.md:
    fwd ~20%, fwd+bwd 2.0x at seq 4096, full train step 1.48x at seq
    2048). Gated to that measured-win regime: at D=128 the flash FORWARD
    measured 2x slower than XLA (only the grad path won), and this route
    also serves eval — configs wanting flash at bigger head dims select
    attention_type='flash' explicitly."""
    return _on_tpu() and seq_len >= 1024 and head_dim <= 64


def sincos_position_table(max_len: int, d_model: int) -> np.ndarray:
    """Classic transformer sin/cos positional table, shape [max_len, d_model]."""
    position = np.arange(max_len, dtype=np.float32)[:, None]
    div_term = np.exp(
        np.arange(0, d_model, 2, dtype=np.float32) * (-np.log(10000.0) / d_model)
    )
    table = np.zeros((max_len, d_model), dtype=np.float32)
    table[:, 0::2] = np.sin(position * div_term)
    table[:, 1::2] = np.cos(position * div_term[: d_model // 2])
    return table


class PositionalEncoding(nn.Module):
    """Adds a fixed sin/cos positional table, then dropout.

    Parity: `PositionalEncoding` (`ray-tune-hpo-regression.py:25-54`), with the
    2-D/3-D indexing bug fixed and the table stored as a module constant (it is
    not a parameter; no need to carry it in the checkpoint).
    """

    d_model: int
    dropout_rate: float = 0.1
    max_len: int = 5000

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        table = jnp.asarray(sincos_position_table(self.max_len, self.d_model))
        # Match x's dtype: under bf16 compute an f32 table would promote the
        # whole residual stream back to f32, silently undoing mixed precision.
        x = x + table[None, : x.shape[1], :].astype(x.dtype)
        return nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)


def apply_rope(x: jnp.ndarray, base: float = 10000.0,
               positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Rotary position embedding over the head dim of [B, S, H, D].

    Rotate-half convention: pairs (x[..., :D/2], x[..., D/2:]) rotate by
    position-dependent angles, so q·k depends only on RELATIVE distance —
    the long-context-friendly alternative to the additive sin/cos table
    (no max_len table, extrapolates past training lengths, and composes
    with sequence sharding: the rotation is elementwise per position, so
    GSPMD shards it with the activations). Math in f32, cast back.
    """
    B, S, H, D = x.shape
    if D % 2:
        raise ValueError(f"RoPE needs an even head dim, got {D}")
    half = D // 2
    pos = (jnp.arange(S, dtype=jnp.float32)
           if positions is None else positions.astype(jnp.float32))
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    angles = pos[:, None] * freqs[None, :]            # [S, half]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return rotated.astype(x.dtype)


class StochasticDepth(nn.Module):
    """Drops an entire residual branch per sample with prob ``rate`` at train time."""

    rate: float

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if self.rate <= 0.0 or deterministic:
            return x
        keep = 1.0 - self.rate
        rng = self.make_rng("dropout")
        mask_shape = (x.shape[0],) + (1,) * (x.ndim - 1)
        mask = jax.random.bernoulli(rng, keep, mask_shape)
        return jnp.where(mask, x / keep, 0.0)


class MultiHeadAttention(nn.Module):
    """Self-attention with a selectable scoring kernel.

    ``attention_type``:
      - "scaled_dot_product" / "multi_head_attention": softmax attention
        (the reference routed both names to torch ``nn.MultiheadAttention``,
        `:138-143`).
      - "linear_attention": true O(n) kernelized linear attention — the
        reference's intent at `:87-117`, minus its O(n^2) scoring and unused
        head args.
      - "blockwise": flash-style blocked softmax for long sequences.

    ``key_dim_scaling`` generalizes the 1/sqrt(d) logit scale to
    d ** -key_dim_scaling (reference's dead C19 knob).
    """

    d_model: int
    num_heads: int
    attention_type: str = "scaled_dot_product"
    key_dim_scaling: float = 0.5
    dropout_rate: float = 0.0
    causal: bool = False
    # None = let each kernel pick its measured-fastest block size (the
    # Pallas flash kernel defaults to large 1024 tiles; the lax.scan
    # blockwise path to 128). An explicit value pins both.
    block_size: Optional[int] = None
    # Sequence parallelism: when set (with a mesh), softmax attention runs
    # sequence-sharded over this mesh axis — the long-context path.
    # Requires the surrounding jit to shard x's sequence dim over `seq_axis`.
    # `seq_parallel_mode` picks the strategy: "ring" (ppermute K/V rotation,
    # parallel/ring_attention.py) or "ulysses" (all_to_all head/seq
    # reshuffle, parallel/ulysses.py — needs divisible head counts).
    seq_axis: Optional[str] = None
    seq_parallel_mode: str = "ring"
    batch_axis: Optional[str] = "dp"
    head_axis: Optional[str] = "tp"
    mesh: Optional[Mesh] = None
    # Compute dtype for projections (params stay float32). The attention
    # kernels themselves already run their softmax/accumulation in float32
    # and cast back to q.dtype (ops/attention.py, ops/pallas_attention.py).
    dtype: Optional[jnp.dtype] = None
    # Rotary position embedding on q/k (relative positions inside the
    # attention scores — the long-context alternative to the model-level
    # additive sin/cos table; see TransformerRegressor.position_encoding).
    rope: bool = False
    # Grouped-query attention: project k/v to this many heads (must divide
    # num_heads) and share each kv head across a query group. None = full
    # MHA; 1 = multi-query. Cuts k/v PROJECTION params/FLOPs by
    # num_heads/num_kv_heads on every path. The Pallas flash kernel (both
    # the explicit "flash" type and the softmax->flash auto-route), the
    # blockwise scan (grouped einsums), ring attention (kv rotates the ring
    # grouped), and Ulysses (when the head split divides) consume kv at
    # kv_heads NATIVELY, with the grouped dK/dV reduction inside the flash
    # backward kernel (ops/pallas_attention.py); linear attention shares
    # per-kv-head state across each query group. Only the dense einsum
    # path broadcasts, just before the kernel (XLA fuses that repeat).
    num_kv_heads: Optional[int] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if self.attention_type not in ATTENTION_TYPES:
            raise ValueError(
                f"Unknown attention_type {self.attention_type!r}; "
                f"expected one of {ATTENTION_TYPES}"
            )
        if self.d_model % self.num_heads != 0:
            raise ValueError(
                f"d_model={self.d_model} not divisible by num_heads={self.num_heads}"
            )
        kv_heads = self.num_kv_heads if self.num_kv_heads is not None else self.num_heads
        if kv_heads <= 0 or self.num_heads % kv_heads != 0:
            # Explicit > 0 check: 0 would silently mean full MHA via
            # truthiness, and negatives pass Python's sign-following modulo
            # (4 % -2 == 0) into an opaque DenseGeneral shape error.
            raise ValueError(
                f"num_kv_heads={kv_heads} must be a positive divisor of "
                f"num_heads={self.num_heads}"
            )
        head_dim = self.d_model // self.num_heads
        B, S, _ = x.shape

        def proj(name, heads):
            return nn.DenseGeneral(
                features=(heads, head_dim), axis=-1, name=name,
                dtype=self.dtype,
            )(x)

        q = proj("query", self.num_heads)
        k = proj("key", kv_heads)
        v = proj("value", kv_heads)
        if self.seq_axis is None:
            # Attention-boundary pins (dp×tp meshes): heads over head_axis,
            # batch over batch_axis — with head-sharded projection kernels
            # this keeps the whole attention block head-local so GSPMD
            # never materializes a replicated [B, S, H, D] intermediate.
            # The seq-parallel paths (ring/ulysses) own their layouts.
            q = constrain_activation(
                q, self.mesh, self.batch_axis, None, self.head_axis, None
            )
            k = constrain_activation(
                k, self.mesh, self.batch_axis, None, self.head_axis, None
            )
            v = constrain_activation(
                v, self.mesh, self.batch_axis, None, self.head_axis, None
            )

        def full_kv(k, v):
            # Broadcast each kv head over its query group for paths WITHOUT
            # native grouped-kv support; the flash and ring paths below skip
            # this and stream kv at kv_heads (see attribute comment).
            if kv_heads != self.num_heads:
                group = self.num_heads // kv_heads
                return jnp.repeat(k, group, axis=2), jnp.repeat(v, group, axis=2)
            return k, v

        if self.rope:
            # Applied to the GLOBAL [B, S, H, D] arrays before any
            # sequence-parallel entry — elementwise per position, so GSPMD
            # shards it with the activations and every downstream kernel
            # (dense/flash/ring/ulysses) sees already-rotated q/k.
            q, k = apply_rope(q), apply_rope(k)

        if self.seq_axis is not None:
            if self.mesh is None:
                raise ValueError(
                    "seq_axis set but no mesh given: ring attention needs the "
                    "device mesh to shard the sequence over"
                )
            if self.attention_type not in (
                "scaled_dot_product", "multi_head_attention", "flash",
                "blockwise",
            ):
                # Ring attention computes exact softmax attention; silently
                # substituting it for a different kernel (e.g. linear
                # attention) would change the math the config asked for.
                raise ValueError(
                    f"attention_type={self.attention_type!r} cannot run "
                    f"sequence-parallel: ring attention implements softmax "
                    f"attention only. Drop seq_axis or use a softmax variant."
                )
            if self.seq_parallel_mode == "ulysses":
                from distributed_machine_learning_tpu.parallel.ulysses import (
                    ulysses_attention as seq_parallel_attention,
                )

                # Ulysses all-to-alls redistribute HEADS over the sp (and
                # tp) axes; grouped kv rides them at kv_heads (all-to-all
                # payload / group) when the split divides, else broadcast.
                # head_split is ulysses' own rule — one definition, no
                # drift; seq_axis membership is validated downstream.
                from distributed_machine_learning_tpu.parallel.ulysses import (
                    head_split,
                )

                if kv_heads % head_split(
                    self.mesh, self.seq_axis, self.head_axis
                ) != 0:
                    k, v = full_kv(k, v)
            elif self.seq_parallel_mode == "ring":
                from distributed_machine_learning_tpu.parallel.ring_attention import (
                    ring_attention as seq_parallel_attention,
                )
                # Ring attention takes kv at kv_heads natively: chunks
                # rotate the ring at the grouped size (ICI payload / group).
                # UNLESS tensor parallelism shards the head axis and the kv
                # head count doesn't divide over it (e.g. MQA's 1 kv head on
                # tp=2) — then grouped kv cannot be laid out on the mesh and
                # the broadcast is required for correctness.
                if (
                    self.head_axis
                    and self.head_axis in self.mesh.axis_names
                    and kv_heads % self.mesh.shape[self.head_axis] != 0
                ):
                    k, v = full_kv(k, v)
            else:
                raise ValueError(
                    f"Unknown seq_parallel_mode {self.seq_parallel_mode!r}; "
                    f"expected 'ring' or 'ulysses'"
                )

            scale = float(head_dim) ** (-self.key_dim_scaling)
            out = seq_parallel_attention(
                q, k, v,
                mesh=self.mesh,
                axis_name=self.seq_axis,
                batch_axis=self.batch_axis,
                head_axis=self.head_axis,
                causal=self.causal,
                scale=scale,
            )
        elif self.attention_type == "linear_attention":
            # linear attention consumes grouped kv natively (per-kv-head
            # state shared across each query group).
            out = linear_attention(q, k, v, causal=self.causal)
        elif self.attention_type == "flash":
            # Hand-written Pallas MXU kernel on TPU; off-TPU the same math
            # runs through the lax.scan blockwise path (Mosaic kernels only
            # compile for TPU backends).
            scale = float(head_dim) ** (-self.key_dim_scaling)
            if _on_tpu():
                from distributed_machine_learning_tpu.ops.pallas_attention import (
                    flash_attention,
                )

                # Block clamping/divisor adjustment happens inside
                # flash_attention (None = its measured-fastest defaults);
                # kv stays at kv_heads — the kernel streams it grouped.
                out = flash_attention(
                    q, k, v, scale=scale, causal=self.causal,
                    block_q=self.block_size, block_k=self.block_size,
                )
            else:
                bs = largest_divisor_block(S, self.block_size or 128)
                q_scaled = q * (scale / (float(head_dim) ** -0.5))
                # blockwise consumes grouped kv natively (grouped einsums).
                out = blockwise_attention(
                    q_scaled, k, v, block_size=bs, causal=self.causal
                )
        elif self.attention_type == "blockwise":
            bs = largest_divisor_block(S, self.block_size or 128)
            out = blockwise_attention(q, k, v, block_size=bs, causal=self.causal)
        else:
            scale = float(head_dim) ** (-self.key_dim_scaling)
            if _route_softmax_to_flash(S, head_dim):
                # Exact same softmax math through the measured-faster
                # Pallas kernel (long sequences on TPU only). Blocks stay
                # None — the kernel's measured-fastest tiles; block_size
                # here is the blockwise-scan knob, and a small value would
                # turn the fast path into the measured-slow 128-tile one.
                from distributed_machine_learning_tpu.ops.pallas_attention import (
                    flash_attention,
                )

                out = flash_attention(
                    q, k, v, scale=scale, causal=self.causal,
                )
            else:
                k, v = full_kv(k, v)
                mask = None
                if self.causal:
                    mask = jnp.tril(jnp.ones((S, S), bool))[None, None, :, :]
                out = dot_product_attention(q, k, v, mask=mask, scale=scale)

        out = nn.DenseGeneral(
            features=self.d_model, axis=(-2, -1), name="out",
            dtype=self.dtype,
        )(out)
        return nn.Dropout(self.dropout_rate)(out, deterministic=deterministic)


class LinearFF(nn.Module):
    """Linear -> ReLU -> Linear feed-forward (`ray-tune-hpo-regression.py:151-155`)."""

    d_model: int
    dim_feedforward: int
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Dense(self.dim_feedforward, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.Dense(self.d_model, dtype=self.dtype)(x)


class DepthwiseSeparableFF(nn.Module):
    """Depthwise (k=3) + pointwise conv feed-forward, projected back to d_model.

    Parity: `DepthwiseSeparableConv` (`ray-tune-hpo-regression.py:59-82`) with
    the missing d_model output projection added so the residual add is always
    shape-correct (the reference only worked when dim_feedforward == d_model).
    flax convs are NWC (batch, seq, channels) natively — no transpose dance.
    """

    d_model: int
    dim_feedforward: int
    kernel_size: int = 3
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        x = nn.Conv(
            features=self.d_model,
            kernel_size=(self.kernel_size,),
            padding="SAME",
            feature_group_count=self.d_model,
            name="depthwise",
            dtype=self.dtype,
        )(x)
        x = nn.Conv(
            features=self.dim_feedforward, kernel_size=(1,), name="pointwise",
            dtype=self.dtype,
        )(x)
        x = nn.relu(x)
        return nn.Dense(self.d_model, name="out_proj", dtype=self.dtype)(x)


class EncoderLayer(nn.Module):
    """Post-LN transformer encoder block.

    Parity: `CustomEncoderLayer` (`ray-tune-hpo-regression.py:122-178`):
    attention -> dropout -> residual -> LN, then FF (linear or depthwise-
    separable, `:148-155`) -> dropout -> residual -> LN, plus working
    stochastic depth on both residual branches.
    """

    d_model: int
    num_heads: int
    dim_feedforward: int
    dropout_rate: float = 0.1
    attention_type: str = "scaled_dot_product"
    key_dim_scaling: float = 0.5
    depthwise_separable_conv: bool = False
    attn_kernel_size: int = 3
    stochastic_depth_rate: float = 0.0
    # Feed-forward selector: "linear" | "depthwise_separable" | "moe".
    # None defers to the legacy `depthwise_separable_conv` bool (the
    # reference's knob, `ray-tune-hpo-regression.py:148-155`).
    feedforward_type: Optional[str] = None
    num_experts: int = 8
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    moe_aux_coef: float = 1e-2
    seq_axis: Optional[str] = None
    seq_parallel_mode: str = "ring"
    batch_axis: Optional[str] = "dp"
    head_axis: Optional[str] = "tp"
    mesh: Optional[Mesh] = None
    # Compute dtype for the whole block (params stay float32). LayerNorm
    # gets it too: its scale/offset params are f32, statistics are computed
    # through flax's f32 promotion internally, and the output lands back in
    # this dtype so the residual stream stays narrow.
    dtype: Optional[jnp.dtype] = None
    rope: bool = False
    num_kv_heads: Optional[int] = None
    # Attention tile override (flash block_q/block_k, blockwise block) —
    # None = the kernel's measured-fastest defaults.
    block_size: Optional[int] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        attn = MultiHeadAttention(
            d_model=self.d_model,
            num_heads=self.num_heads,
            attention_type=self.attention_type,
            key_dim_scaling=self.key_dim_scaling,
            dropout_rate=self.dropout_rate,
            seq_axis=self.seq_axis,
            seq_parallel_mode=self.seq_parallel_mode,
            batch_axis=self.batch_axis,
            head_axis=self.head_axis,
            mesh=self.mesh,
            dtype=self.dtype,
            rope=self.rope,
            num_kv_heads=self.num_kv_heads,
            block_size=self.block_size,
            name="attention",
        )(x, deterministic=deterministic)
        attn = StochasticDepth(self.stochastic_depth_rate)(attn, deterministic)
        x = nn.LayerNorm(name="norm1", dtype=self.dtype)(x + attn)
        # Residual-stream pin: batch over dp (seq over sp when used),
        # d_model replicated — the Megatron layout the TP rules assume.
        x = constrain_activation(
            x, self.mesh, self.batch_axis, self.seq_axis, None
        )

        ff_type = self.feedforward_type or (
            "depthwise_separable" if self.depthwise_separable_conv else "linear"
        )
        if ff_type == "depthwise_separable":
            ff = DepthwiseSeparableFF(
                d_model=self.d_model,
                dim_feedforward=self.dim_feedforward,
                kernel_size=self.attn_kernel_size,
                dtype=self.dtype,
                name="ff",
            )(x)
        elif ff_type == "moe":
            # MoEFF follows its input's dtype (router pinned f32 inside).
            ff = MoEFF(
                d_model=self.d_model,
                dim_feedforward=self.dim_feedforward,
                num_experts=self.num_experts,
                top_k=self.expert_top_k,
                capacity_factor=self.capacity_factor,
                aux_loss_coef=self.moe_aux_coef,
                name="ff",
            )(x)
        elif ff_type == "linear":
            ff = LinearFF(
                d_model=self.d_model, dim_feedforward=self.dim_feedforward,
                dtype=self.dtype, name="ff"
            )(x)
        else:
            raise ValueError(
                f"Unknown feedforward_type {ff_type!r}; expected "
                f"'linear', 'depthwise_separable', or 'moe'"
            )
        ff = nn.Dropout(self.dropout_rate)(ff, deterministic=deterministic)
        ff = StochasticDepth(self.stochastic_depth_rate)(ff, deterministic)
        out = nn.LayerNorm(name="norm2", dtype=self.dtype)(x + ff)
        return constrain_activation(
            out, self.mesh, self.batch_axis, self.seq_axis, None
        )
