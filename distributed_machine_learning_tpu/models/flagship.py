"""The sharded flagship: a config that CANNOT fit one device.

ROADMAP item 1's proof obligation — "a flagship config that cannot fit one
chip's HBM trains end to end through tune.run on a 2-D mesh" — needs the
claim to be *checkable*, not asserted: :func:`param_opt_bytes` prices a
config's parameter + optimizer state via ``jax.eval_shape`` (pure shape
math, nothing allocated), :func:`single_chip_hbm_bytes` reads the device's
budget, and :func:`flagship_sharded_config` grows ``d_model`` by doublings
until the price exceeds the budget — so the returned config provably needs
the mesh it asks for.  Tests assert ``param_opt_bytes(cfg) >
single_chip_hbm_bytes()`` instead of trusting a hand-picked shape.

On the CPU test platform the 8 virtual devices share host RAM, so the
"HBM" budget is a virtual one (``DML_CPU_DEVICE_BUDGET_BYTES``, default
8 MiB) — small enough that the derived flagship trains in seconds in
tier-1 while still exercising the exact code path: params + adam moments
genuinely exceed the per-device budget and only the dp×tp layout spreads
them.  On TPU the budget is the real per-chip HBM (``memory_stats`` when
the runtime exposes it, a per-generation fallback otherwise) and the same
derivation yields a multi-billion-parameter config for the bench
``sharded_flagship`` section.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

# Per-chip HBM when the runtime exposes no memory_stats: v2/v3 8/16 GiB
# cores, v4 32 GiB, v5e 16 GiB — 16 GiB is the safe middle.  The CPU test
# platform gets a deliberately tiny VIRTUAL budget (see module docstring).
_TPU_HBM_FALLBACK_BYTES = 16 << 30
_CPU_VIRTUAL_BUDGET_BYTES = 8 << 20


def single_chip_hbm_bytes(device=None) -> int:
    """The accelerator-memory budget of one device, in bytes."""
    if device is None:
        import jax

        device = jax.devices()[0]
    platform = getattr(device, "platform", "cpu")
    if platform == "cpu":
        return int(
            os.environ.get(
                "DML_CPU_DEVICE_BUDGET_BYTES", _CPU_VIRTUAL_BUDGET_BYTES
            )
        )
    try:
        stats = device.memory_stats()
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit
    except Exception:  # noqa: BLE001 - not every runtime exposes stats
        pass
    return _TPU_HBM_FALLBACK_BYTES


def param_opt_bytes(config: Dict[str, Any], features: int = 16,
                    optimizer: Optional[str] = None) -> int:
    """Parameter + optimizer-state bytes of ``config``, by shape math only.

    ``jax.eval_shape`` traces ``model.init`` and ``tx.init`` abstractly —
    no array is ever materialized, so pricing a 100 GiB config costs
    milliseconds (safe to call in tests and at trainable startup).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_machine_learning_tpu.models import build_model
    from distributed_machine_learning_tpu.ops.optimizers import make_optimizer

    model = build_model(dict(config, mesh=None))
    sample = jax.ShapeDtypeStruct(
        (1, int(config.get("max_seq_length", 64)), int(features)),
        jnp.float32,
    )

    def init(x):
        return model.init(
            {"params": jax.random.key(0), "dropout": jax.random.key(1)},
            x, deterministic=True,
        )

    variables = jax.eval_shape(init, sample)
    params = variables["params"]
    tx = make_optimizer(
        str(optimizer or config.get("optimizer", "adam")),
        learning_rate=1e-3,
    )
    opt_state = jax.eval_shape(tx.init, params)

    def nbytes(tree) -> int:
        return sum(
            int(np.prod(leaf.shape, dtype=np.int64)) * leaf.dtype.itemsize
            for leaf in jax.tree.leaves(tree)
            if hasattr(leaf, "shape")
        )

    return nbytes(params) + nbytes(opt_state)


def flagship_sharded_config(
    budget_bytes: Optional[int] = None,
    *,
    mesh_shape: Optional[Dict[str, int]] = None,
    seq_len: int = 16,
    features: int = 16,
    batch_size: int = 32,
    num_layers: int = 2,
    max_d_model: int = 1 << 15,
) -> Dict[str, Any]:
    """The smallest power-of-two ``d_model`` transformer whose params +
    adam moments exceed ``budget_bytes`` (default: this platform's
    :func:`single_chip_hbm_bytes`), configured for a 2-D (dp, tp) mesh.

    The returned dict is a complete trial config for
    ``tune.train_sharded_regressor`` — callers add data-dependent keys
    (``num_epochs``, lr) and pass ``resources_per_trial`` matching
    ``mesh_shape`` (default ``{"dp": 2, "tp": 4}``, the 8-device tier-1
    mesh).  Raises if no ``d_model`` up to ``max_d_model`` exceeds the
    budget — a mis-set budget must fail loudly, not silently return a
    config that fits one chip.
    """
    if budget_bytes is None:
        budget_bytes = single_chip_hbm_bytes()
    mesh_shape = dict(mesh_shape or {"dp": 2, "tp": 4})
    d_model = 64
    while d_model <= max_d_model:
        config = {
            "model": "transformer",
            "d_model": d_model,
            "num_heads": 8,
            "num_layers": num_layers,
            "dim_feedforward": 4 * d_model,
            "dropout": 0.0,
            "max_seq_length": seq_len,
            "batch_size": batch_size,
            "optimizer": "adam",
            "mesh_shape": mesh_shape,
            # Remat keeps the per-block activation footprint O(1) blocks —
            # the knob that makes the over-budget config schedulable at
            # all on real HBM (dots_saveable: recompute elementwise only).
            "remat": True,
            "remat_policy": "dots_saveable",
        }
        if param_opt_bytes(config, features=features) > budget_bytes:
            return config
        d_model *= 2
    raise ValueError(
        f"no d_model <= {max_d_model} exceeds budget_bytes={budget_bytes}"
    )
