"""Recurrent regressors (LSTM / GRU) for windowed time series.

Beyond-parity model family (the reference's zoo is transformer-only,
`/root/reference/ray-tune-hpo-regression.py:183-240`): classic recurrent
baselines the same search spaces can sweep against the transformer.

TPU shape: the recurrence runs as ONE ``lax.scan`` over time via
``flax.linen.RNN`` — a rolled loop XLA compiles once (cheap compiles, the
HPO-critical property) whose per-step matmuls batch over the full
minibatch. Sequences here are short windows (96 steps in the reference's
pipeline), so a scan is the right tool; for long sequences the
transformer + ring/Ulysses path is the scalable one.
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class RNNRegressor(nn.Module):
    """Stacked LSTM/GRU encoder + MLP regression head.

    ``cell_type``: "lstm" | "gru". Layers stack with inter-layer dropout;
    the last time step's top-layer hidden state feeds the head (the same
    last-token pooling the transformer family uses,
    `ray-tune-hpo-regression.py:235`).
    """

    hidden_size: int = 64
    num_layers: int = 1
    cell_type: str = "lstm"
    dropout_rate: float = 0.0
    head_hidden_sizes: Sequence[int] = (64,)
    out_features: int = 1
    # Compute dtype (params stay float32). Note: the recurrence compounds
    # rounding across time steps, so bf16 here trades more precision than
    # in feed-forward families — fine for short windows, opt-in always.
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, deterministic: bool = True) -> jnp.ndarray:
        if self.cell_type == "lstm":
            make_cell = lambda i: nn.LSTMCell(
                self.hidden_size, dtype=self.dtype, name=f"lstm_{i}"
            )
        elif self.cell_type == "gru":
            make_cell = lambda i: nn.GRUCell(
                self.hidden_size, dtype=self.dtype, name=f"gru_{i}"
            )
        else:
            raise ValueError(
                f"Unknown cell_type {self.cell_type!r}; expected 'lstm' or 'gru'"
            )
        if x.ndim == 2:  # tabular input: one-step sequence (family contract)
            x = x[:, None, :]
        h = x
        for i in range(self.num_layers):
            h = nn.RNN(make_cell(i), name=f"rnn_{i}")(h)
            if i < self.num_layers - 1:
                h = nn.Dropout(self.dropout_rate)(
                    h, deterministic=deterministic
                )
        h = h[:, -1, :]  # last-step pooling
        for j, width in enumerate(self.head_hidden_sizes):
            h = nn.relu(nn.Dense(width, dtype=self.dtype, name=f"head_{j}")(h))
        return nn.Dense(self.out_features, dtype=self.dtype, name="out")(h)
