"""One content-addressed store for every byte-durability scheme.

Five subsystems invented storage-with-integrity independently (ckpt chunk
sha256 + COMMIT, compilecache's ArtifactRegistry, serve bundle manifests,
the sha256-keyed dataset cache, obs exports).  This module is the single
layer they now share.  A store rooted at ``<root>`` (any ``tune.storage``
scheme) holds::

    <root>/blobs/<hh>/<sha256>   immutable blobs, named by their content
                                 hash (first-publish-wins; a re-publish of
                                 identical bytes is a dedup hit, not a
                                 write), fsync'd on local filesystems
    <root>/refs/<name>           small mutable JSON refs, updated via the
                                 backend's tmp+os.replace write (the
                                 DML020 contract) — each names a manifest

A *manifest* is itself a blob: a JSON object whose ``store_chunks`` key
flat-lists every blob digest the referencing object needs.  Reachability
is therefore one hop deep and schema-agnostic: GC walks refs ->
manifests -> chunks and never needs to understand checkpoint indexes,
compile-artifact packs, or dataset caches.

GC is pin-then-scan: a writer opens a :meth:`ContentStore.pin` session
and registers every digest BEFORE its ref lands, and the collector
snapshots the pin table BEFORE scanning blobs — so a publish racing a
sweep keeps its new blobs even though no ref names them yet.  An
optional ``min_age_s`` grace additionally protects blobs written by
*other* processes (local scheme only, where mtimes exist).

Retry/chaos/fallback is not reimplemented here: every byte moves through
``tune.storage.get_storage``, so the chaos ``FaultyStorage`` wrapper and
``RetryingStorage`` compose around the store exactly as they do around
checkpoints.  Two store-specific chaos hooks ride the active plan:
``blob_corrupt_on_publish`` (a published blob's bytes no longer match
its name — ``verify`` must catch it) and ``kill_during_ref_flip`` (the
writer dies between preparing and landing a ref — the OLD ref survives
intact, the atomicity contract).
"""

from __future__ import annotations

import hashlib
import json
import os
import posixpath
import re
import time
from typing import Any, Dict, Iterator, List, Optional, Set, Tuple

from distributed_machine_learning_tpu.analysis.locks import named_lock
from distributed_machine_learning_tpu.store.metrics import get_metrics
from distributed_machine_learning_tpu.tune.storage import get_storage

BLOBS_DIR = "blobs"
REFS_DIR = "refs"
MANIFEST_CHUNKS_KEY = "store_chunks"
STORE_DIR_NAME = ".cas"

ROOT_ENV_VAR = "DML_STORE_ROOT"
ENABLE_ENV_VAR = "DML_STORE_CKPT"

_DIGEST_RE = re.compile(r"^[0-9a-f]{64}$")
_REF_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._\-]*$")


class StoreCorruptionError(Exception):
    """Stored blob bytes no longer hash to their name."""


def store_enabled() -> bool:
    """Whether checkpoint/export write paths publish through the store
    (``DML_STORE_CKPT``; default on — ``0`` restores the pre-CAS path,
    which is also what the bench ``store`` section compares against)."""
    return os.environ.get(ENABLE_ENV_VAR, "1") not in ("0", "false", "no")


def store_root_for(path: str) -> str:
    """The store root serving ``path``: ``$DML_STORE_ROOT`` when set
    (one experiment-wide store -> cross-trial dedup), else a ``.cas``
    sibling of ``path`` (``<parent>/.cas`` — one store per checkpoint
    directory, which is where generation chains and PBT populations
    already share bytes)."""
    env = os.environ.get(ROOT_ENV_VAR)
    if env:
        return env
    backend, p = get_storage(str(path))
    parent = posixpath.dirname(p.rstrip("/")) or p
    return backend.join(parent, STORE_DIR_NAME)


def ref_name_for_path(kind: str, path: str) -> str:
    """Deterministic flat ref name for an object at ``path`` —
    re-computable by anyone who knows the path (delete paths, GC tools)."""
    digest = hashlib.sha256(str(path).rstrip("/").encode()).hexdigest()
    return f"{kind}-{digest[:24]}"


# -- pin table (process-global, keyed by store root) ---------------------------

_pin_lock = named_lock("store.pins")
_pin_table: Dict[str, Dict[int, Set[str]]] = {}
_pin_seq = [0]


class PinSession:
    """In-flight publish protection: digests added here are invisible to
    GC's collectable set until the session closes (which the writer does
    only AFTER its ref landed)."""

    def __init__(self, root: str):
        self._root = root
        with _pin_lock:
            _pin_seq[0] += 1
            self._id = _pin_seq[0]
            _pin_table.setdefault(root, {})[self._id] = set()

    def add(self, digest: str) -> None:
        with _pin_lock:
            sessions = _pin_table.get(self._root)
            if sessions is not None and self._id in sessions:
                sessions[self._id].add(digest)

    def release(self) -> None:
        with _pin_lock:
            sessions = _pin_table.get(self._root)
            if sessions is not None:
                sessions.pop(self._id, None)
                if not sessions:
                    _pin_table.pop(self._root, None)

    def __enter__(self) -> "PinSession":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def _pinned_digests(root: str) -> Set[str]:
    with _pin_lock:
        out: Set[str] = set()
        for digests in _pin_table.get(root, {}).values():
            out |= digests
        return out


# -- the store -----------------------------------------------------------------


class ContentStore:
    """Hash-keyed immutable blobs + atomic mutable refs at one root."""

    def __init__(self, root: str):
        self.root = str(root)

    # get_storage is consulted PER OPERATION (not cached at construction)
    # so a chaos plan activated after the store was created still wraps
    # every byte op — the same late-binding contract ckpt/format.py has.
    def _be(self) -> Tuple[Any, str]:
        return get_storage(self.root)

    # -- blobs ---------------------------------------------------------------

    def blob_path(self, digest: str) -> str:
        backend, p = self._be()
        return backend.join(p, BLOBS_DIR, digest[:2], digest)

    def local_blob_path(self, digest: str) -> Optional[str]:
        """Filesystem path of a blob for mmap-style consumers
        (``np.load(mmap_mode='r')``); None on non-local schemes."""
        if "://" in self.root and not self.root.startswith("file://"):
            return None
        path = self.blob_path(digest)
        return path if os.path.exists(path) else None

    def has_blob(self, digest: str) -> bool:
        backend, _ = self._be()
        return backend.exists(self.blob_path(digest))

    def put_blob(self, data: bytes) -> str:
        """Publish ``data``; returns its digest.  An existing blob of the
        same content is a dedup hit — no bytes move."""
        digest = hashlib.sha256(data).hexdigest()
        m = get_metrics()
        m.add("puts")
        m.add("bytes_logical", len(data))
        backend, _ = self._be()
        path = self.blob_path(digest)
        if backend.exists(path):
            m.add("dedup_hits")
            return digest
        payload = data
        plan = _active_plan()
        if plan is not None:
            payload = plan.corrupt_blob_publish(path, payload)
        backend.write_bytes(path, payload)
        self._fsync_local(path)
        m.add("bytes_physical", len(data))
        return digest

    def get_blob(self, digest: str, verify: bool = False) -> Optional[bytes]:
        backend, _ = self._be()
        data = backend.read_bytes(self.blob_path(digest))
        if data is None:
            return None
        m = get_metrics()
        m.add("blob_reads")
        m.add("read_bytes", len(data))
        if verify and hashlib.sha256(data).hexdigest() != digest:
            raise StoreCorruptionError(
                f"blob {digest} under {self.root} fails its content hash"
            )
        return data

    def iter_blobs(self) -> Iterator[str]:
        backend, p = self._be()
        blobs_dir = backend.join(p, BLOBS_DIR)
        for prefix in backend.listdir(blobs_dir):
            if len(prefix) != 2:
                continue
            for name in backend.listdir(backend.join(blobs_dir, prefix)):
                if _DIGEST_RE.match(name):
                    yield name

    def _blob_size(self, digest: str) -> int:
        local = self.local_blob_path(digest)
        if local is not None:
            try:
                return os.path.getsize(local)
            except OSError:
                return 0
        backend, _ = self._be()
        data = backend.read_bytes(self.blob_path(digest))
        return len(data) if data is not None else 0

    @staticmethod
    def _fsync_local(path: str) -> None:
        """Durability for local blobs: the backend's tmp+replace makes the
        write atomic; fsync makes it survive power loss (fsync flushes the
        inode's pages regardless of which fd wrote them)."""
        if not os.path.exists(path):
            return
        try:
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass  # non-POSIX corners: atomicity still holds

    # -- manifests -----------------------------------------------------------

    def put_manifest(self, payload: Dict[str, Any]) -> str:
        """Store ``payload`` (which must flat-list its blob digests under
        ``store_chunks``) as a manifest blob; returns the manifest digest."""
        chunks = payload.get(MANIFEST_CHUNKS_KEY)
        if not isinstance(chunks, list):
            raise ValueError(
                f"manifest payload needs a {MANIFEST_CHUNKS_KEY!r} list "
                f"(got {type(chunks).__name__}) — GC walks it"
            )
        return self.put_blob(
            json.dumps(payload, sort_keys=True).encode()
        )

    def read_manifest(self, digest: str) -> Optional[Dict[str, Any]]:
        data = self.get_blob(digest)
        if data is None:
            return None
        try:
            doc = json.loads(data)
        except ValueError:
            return None
        return doc if isinstance(doc, dict) else None

    # -- refs ----------------------------------------------------------------

    def _ref_path(self, name: str) -> str:
        if not _REF_NAME_RE.match(name):
            raise ValueError(f"invalid ref name {name!r}")
        backend, p = self._be()
        return backend.join(p, REFS_DIR, name)

    def set_ref(
        self,
        name: str,
        manifest_digest: str,
        meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Point ref ``name`` at ``manifest_digest`` — atomically (the
        backend's tmp+``os.replace`` write), so a reader sees the old
        target or the new one, never a torn ref."""
        path = self._ref_path(name)
        plan = _active_plan()
        if plan is not None:
            # kill_during_ref_flip: the writer dies before the replace
            # lands; the previous ref value must survive untouched.
            plan.maybe_kill_ref_flip(path)
        doc = {"manifest": manifest_digest, "updated_at": time.time()}
        if meta:
            doc["meta"] = dict(meta)
        backend, _ = self._be()
        backend.write_bytes(path, json.dumps(doc, sort_keys=True).encode())
        self._fsync_local(path)
        get_metrics().add("ref_updates")

    def read_ref(self, name: str) -> Optional[Dict[str, Any]]:
        backend, _ = self._be()
        raw = backend.read_bytes(self._ref_path(name))
        if raw is None:
            return None
        try:
            doc = json.loads(raw)
        except ValueError:
            return None
        return doc if isinstance(doc, dict) else None

    def delete_ref(self, name: str) -> None:
        backend, _ = self._be()
        backend.delete(self._ref_path(name))
        get_metrics().add("ref_deletes")

    def list_refs(self) -> List[str]:
        backend, p = self._be()
        return [
            n for n in backend.listdir(backend.join(p, REFS_DIR))
            if _REF_NAME_RE.match(n)
        ]

    # -- pins ----------------------------------------------------------------

    def pin(self) -> PinSession:
        return PinSession(self.root)

    # -- reachability / GC ---------------------------------------------------

    def reachable(self) -> Tuple[Set[str], int, int]:
        """``(live_digests, refs_walked, broken_refs)`` — refs ->
        manifests -> chunks.  A ref whose manifest is unreadable counts
        as broken (its chunks cannot be enumerated; ``verify``/restore
        is the tool that diagnoses it)."""
        live: Set[str] = set()
        refs = 0
        broken = 0
        for name in self.list_refs():
            refs += 1
            doc = self.read_ref(name)
            if doc is None:
                broken += 1
                continue
            digest = doc.get("manifest")
            if not isinstance(digest, str):
                broken += 1
                continue
            live.add(digest)
            manifest = self.read_manifest(digest)
            if manifest is None:
                broken += 1
                continue
            for chunk in manifest.get(MANIFEST_CHUNKS_KEY) or []:
                if isinstance(chunk, str):
                    live.add(chunk)
        return live, refs, broken

    def gc(
        self, dry_run: bool = False, min_age_s: float = 0.0
    ) -> Dict[str, Any]:
        """Collect unreachable blobs.  Pin-then-scan: the in-process pin
        table is snapshotted BEFORE refs and blobs are walked, so a
        publish in flight during the sweep keeps its blobs.  ``min_age_s``
        additionally retains young blobs (cross-process writers on local
        storage)."""
        pinned = _pinned_digests(self.root)
        live, refs, broken = self.reachable()
        now = time.time()
        collected = retained = 0
        reclaimed = 0
        backend, _ = self._be()
        for digest in list(self.iter_blobs()):
            if digest in live or digest in pinned:
                retained += 1
                continue
            if min_age_s > 0 and self._age_s(digest, now) < min_age_s:
                retained += 1
                continue
            size = self._blob_size(digest)
            if not dry_run:
                backend.delete(self.blob_path(digest))
            collected += 1
            reclaimed += size
        m = get_metrics()
        if not dry_run:
            m.add("gc_runs")
            m.add("gc_collected", collected)
            m.add("gc_retained", retained)
            m.add("gc_reclaimed_bytes", reclaimed)
        return {
            "dry_run": bool(dry_run),
            "collected": collected,
            "retained": retained,
            "reclaimed_bytes": reclaimed,
            "refs": refs,
            "broken_refs": broken,
        }

    def _age_s(self, digest: str, now: float) -> float:
        local = self.local_blob_path(digest)
        if local is None:
            return float("inf")  # no mtimes: pins are the only guard
        try:
            return max(0.0, now - os.path.getmtime(local))
        except OSError:
            return float("inf")

    # -- audit ---------------------------------------------------------------

    def verify(self) -> Dict[str, Any]:
        """Re-hash every blob; report the ones whose bytes no longer match
        their name (bit rot, or a chaos ``blob_corrupt_on_publish``)."""
        m = get_metrics()
        checked = 0
        corrupt: List[str] = []
        backend, _ = self._be()
        for digest in self.iter_blobs():
            data = backend.read_bytes(self.blob_path(digest))
            if data is None:
                continue
            checked += 1
            m.add("verify_blobs")
            if hashlib.sha256(data).hexdigest() != digest:
                corrupt.append(digest)
                m.add("verify_corrupt")
        return {"blobs": checked, "corrupt": sorted(corrupt)}

    def stats(self) -> Dict[str, Any]:
        """Physical truth from storage plus the process counters: blob and
        ref counts, physical bytes on disk, logical/physical counter bytes
        and their dedup ratio."""
        physical = 0
        blobs = 0
        for digest in self.iter_blobs():
            blobs += 1
            physical += self._blob_size(digest)
        snap = get_metrics().snapshot()
        logical = snap.get("bytes_logical", 0)
        written = snap.get("bytes_physical", 0)
        return {
            "root": self.root,
            "blobs": blobs,
            "refs": len(self.list_refs()),
            "physical_bytes": physical,
            "counters": snap,
            "dedup_ratio": (
                round(float(written) / float(logical), 4)
                if logical else 1.0
            ),
        }


def _active_plan():
    from distributed_machine_learning_tpu import chaos

    return chaos.active_plan()


# -- store cache ---------------------------------------------------------------

_stores_lock = named_lock("store.instances")
_stores: Dict[str, ContentStore] = {}


def get_store(root: str) -> ContentStore:
    """The (cached) store rooted at ``root`` — ContentStore carries no
    open handles, so caching is just identity stability for pin tables."""
    key = str(root)
    with _stores_lock:
        store = _stores.get(key)
        if store is None:
            store = _stores[key] = ContentStore(key)
        return store
