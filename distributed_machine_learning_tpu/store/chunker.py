"""Row-aligned sub-chunking: the piece boundaries that make dedup land.

A checkpoint chunk is the raw bytes of one shard of one leaf.  Splitting
it at arbitrary byte offsets would make dedup brittle — a one-row change
in a PBT exploit shifts nothing, but piece boundaries that ignore the
array's row structure turn "one row changed" into "every piece changed"
the moment shapes differ between writers.  Splitting at LEADING-AXIS row
boundaries instead means a donor row copied between population members,
or an optimizer leaf untouched between generation N and N+1, hashes to
the same blob every time: content addressing does the rest.

``rows_per_piece = max(1, target_piece_bytes // row_stride)`` — small
leaves become a single piece (no pathological per-row blob explosion),
large leaves split near the target size so a local edit dirties one
piece, not the whole leaf.
"""

from __future__ import annotations

import os
from typing import List, Tuple

DEFAULT_TARGET_PIECE_BYTES = 256 * 1024
CHUNK_BYTES_ENV_VAR = "DML_STORE_CHUNK_BYTES"


def target_piece_bytes() -> int:
    """The configured piece-size target (``DML_STORE_CHUNK_BYTES``,
    default 256 KiB); values < 1 fall back to the default."""
    raw = os.environ.get(CHUNK_BYTES_ENV_VAR)
    if not raw:
        return DEFAULT_TARGET_PIECE_BYTES
    try:
        val = int(raw)
    except ValueError:
        return DEFAULT_TARGET_PIECE_BYTES
    return val if val >= 1 else DEFAULT_TARGET_PIECE_BYTES


def split_row_aligned(
    nbytes: int, row_stride: int, target: int = 0
) -> List[Tuple[int, int]]:
    """``(offset, length)`` piece spans covering ``[0, nbytes)``.

    ``row_stride`` is the byte width of one leading-axis row (0 for
    scalars / unknown layout -> a single piece).  Pieces are whole
    multiples of ``row_stride`` except the last, which absorbs any tail.
    """
    if nbytes <= 0:
        return []
    target = target if target > 0 else target_piece_bytes()
    if row_stride <= 0 or row_stride >= nbytes:
        return [(0, nbytes)]
    rows_per_piece = max(1, target // row_stride)
    piece = rows_per_piece * row_stride
    spans: List[Tuple[int, int]] = []
    off = 0
    while off < nbytes:
        ln = min(piece, nbytes - off)
        # The final fragment shorter than one row rides with its
        # predecessor so every boundary except EOF is row-aligned.
        if 0 < nbytes - (off + ln) < row_stride:
            ln = nbytes - off
        spans.append((off, ln))
        off += ln
    return spans
