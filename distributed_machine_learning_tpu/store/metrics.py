"""Process-wide content-store counters.

The CAS perf claim (ISSUE 20) must be *measured*, not architectural:
``bytes_logical`` counts every byte a writer asked the store to keep,
``bytes_physical`` only the bytes that actually landed as new blobs —
their ratio IS the dedup win the bench ``store`` section reports, and a
``dedup_hits`` that stays 0 across a PBT exploit or a keep-K generation
chain is the chunking-regression signal the operations runbook keys on.

Registered as the ``store`` family in the unified metrics registry
(obs/registry.py), same shape as ``ckpt/metrics.py``: flight dumps,
``/metrics`` and head aggregation see ``store/puts``,
``store/dedup_hits``, ... for free.  Drivers scope the process-wide
totals to one run via :meth:`StoreMetrics.delta_since`.
"""

from __future__ import annotations

from typing import Dict

from distributed_machine_learning_tpu.analysis.locks import named_lock


class StoreMetrics:
    """Thread-safe counters for content-store activity."""

    _FIELDS = (
        "puts",                # blob publish attempts (dedup hits included)
        "dedup_hits",          # publishes answered by an existing blob
        "bytes_logical",       # bytes writers asked the store to keep
        "bytes_physical",      # bytes that landed as NEW blob files
        "blob_reads",
        "read_bytes",
        "ref_updates",
        "ref_deletes",
        "ref_copies",          # chunks re-published by reference only
        "gc_runs",
        "gc_collected",
        "gc_retained",
        "gc_reclaimed_bytes",
        "verify_blobs",
        "verify_corrupt",
    )

    def __init__(self):
        self._lock = named_lock("store.metrics")
        self._c: Dict[str, float] = {k: 0 for k in self._FIELDS}

    def add(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + value

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self._c.items()
            }

    def delta_since(self, baseline: Dict[str, float]) -> Dict[str, float]:
        """Counters accumulated since ``baseline`` (a prior snapshot)."""
        snap = self.snapshot()
        return {k: round(v - baseline.get(k, 0), 4) for k, v in snap.items()}

    def dedup_ratio(self) -> float:
        """``bytes_physical / bytes_logical`` (1.0 on an empty store):
        1.0 = no sharing at all, 0.0 = everything was already stored."""
        with self._lock:
            logical = self._c.get("bytes_logical", 0)
            if logical <= 0:
                return 1.0
            return float(self._c.get("bytes_physical", 0)) / float(logical)

    def reset(self) -> None:
        """Test hook: zero every counter."""
        with self._lock:
            self._c = {k: 0 for k in self._FIELDS}


_metrics = StoreMetrics()

from distributed_machine_learning_tpu.obs.registry import (  # noqa: E402
    get_registry as _obs_registry,
)

_obs_registry().register_family("store", _metrics)


def get_metrics() -> StoreMetrics:
    """The process-wide store counters (one instance per process)."""
    return _metrics
