"""store/ — the content-addressed store (ISSUE 20, ROADMAP item 4).

Public surface:

* :class:`ContentStore` / :func:`get_store` — blobs, refs, manifests,
  pin-then-scan GC, verify, stats.
* :func:`store_root_for` / :func:`store_enabled` /
  :func:`ref_name_for_path` — where a writer's store lives and whether
  the CAS write paths are on.
* :func:`split_row_aligned` / :func:`target_piece_bytes` — the dedup
  chunking contract.
* :func:`get_metrics` — the ``store`` counter family
  (``puts``, ``dedup_hits``, ``bytes_logical``, ``bytes_physical``,
  ``gc_collected``, ``gc_retained``, ...).
"""

from distributed_machine_learning_tpu.store.chunker import (
    CHUNK_BYTES_ENV_VAR,
    DEFAULT_TARGET_PIECE_BYTES,
    split_row_aligned,
    target_piece_bytes,
)
from distributed_machine_learning_tpu.store.core import (
    BLOBS_DIR,
    ENABLE_ENV_VAR,
    MANIFEST_CHUNKS_KEY,
    REFS_DIR,
    ROOT_ENV_VAR,
    STORE_DIR_NAME,
    ContentStore,
    PinSession,
    StoreCorruptionError,
    get_store,
    ref_name_for_path,
    store_enabled,
    store_root_for,
)
from distributed_machine_learning_tpu.store.metrics import (
    StoreMetrics,
    get_metrics,
)

__all__ = [
    "BLOBS_DIR",
    "CHUNK_BYTES_ENV_VAR",
    "DEFAULT_TARGET_PIECE_BYTES",
    "ENABLE_ENV_VAR",
    "MANIFEST_CHUNKS_KEY",
    "REFS_DIR",
    "ROOT_ENV_VAR",
    "STORE_DIR_NAME",
    "ContentStore",
    "PinSession",
    "StoreCorruptionError",
    "StoreMetrics",
    "get_metrics",
    "get_store",
    "ref_name_for_path",
    "split_row_aligned",
    "store_enabled",
    "store_root_for",
    "target_piece_bytes",
]
