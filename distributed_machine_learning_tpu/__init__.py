"""distributed_machine_learning_tpu: a TPU-native distributed HPO framework.

A brand-new JAX/XLA framework with the capabilities of
`Ravikiran-Bhonagiri/Distributed-Machine-Learning` (see SURVEY.md): many
concurrent jit-compiled regression-training trials packed onto TPU cores under
native ASHA/PBT/median schedulers with random/grid/Bayesian search, per-epoch
metric streaming, pytree checkpoint/restore, and an experiment store with
best-config analysis — no Ray, no torch in the loop.
"""

from distributed_machine_learning_tpu import (
    data,
    liveness,
    models,
    ops,
    tune,
    utils,
)

__version__ = "0.1.0"

__all__ = [
    "data", "liveness", "models", "ops", "tune", "utils", "__version__",
]
