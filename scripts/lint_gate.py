#!/usr/bin/env python3
"""CI diff gate: ``dml-tpu lint --changed --format=sarif`` for BOTH tiers.

The checked-in entry point CI (and pre-push hooks) call so the gate's
flags live in ONE place:

    python scripts/lint_gate.py [--ref REF] [--out lint.sarif] [--full]

* ``--ref`` (default: ``origin/main`` if it resolves, else ``HEAD``) —
  findings are filtered to files changed vs the ref; the whole tree is
  still parsed/audited so cross-file and program-level checks judge the
  change against the full project.
* ``--out`` — where the SARIF 2.1.0 report lands (CI annotators upload
  it); the human-readable text report goes to stdout either way.
* ``--full`` — gate the whole tree instead of the diff (the nightly /
  release mode).
* ``--no-jax`` — AST tier only, for hosts without a working jax install.
* ``--no-perf-guard`` — skip the obs-plane disabled-path overhead check.

The gate also runs the observability-plane overhead guard
(``DML_OBS_PERF_GUARD=1`` in its own environment): the tracing-DISABLED
``obs.span()`` path must stay at a few hundred ns per call with zero net
allocation, or always-on instrumentation in epoch/request hot paths stops
being free — a regression there gates the diff like a lint finding.

Exit code is the lint's: 0 clean, 1 unsuppressed findings, 2 usage/git
trouble — the same contract as ``dml-tpu lint`` itself.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _default_ref() -> str:
    probe = subprocess.run(
        ["git", "rev-parse", "--verify", "--quiet", "origin/main"],
        cwd=REPO, capture_output=True, text=True,
    )
    return "origin/main" if probe.returncode == 0 else "HEAD"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--ref", default=None,
                   help="diff base (default: origin/main, else HEAD)")
    p.add_argument("--out", default="lint.sarif",
                   help="SARIF output path (default: ./lint.sarif)")
    p.add_argument("--full", action="store_true",
                   help="lint the whole tree, not just the diff")
    p.add_argument("--no-jax", action="store_true",
                   help="skip the program-level (jaxlint) tier")
    p.add_argument("--no-perf-guard", action="store_true",
                   help="skip the obs disabled-path overhead guard")
    p.add_argument("--no-quant-smoke", action="store_true",
                   help="skip the quantize-export-load smoke")
    p.add_argument("--no-loop-smoke", action="store_true",
                   help="skip the drift-retrain-promote loop smoke")
    p.add_argument("--no-head-smoke", action="store_true",
                   help="skip the head-crash auto-resume smoke")
    p.add_argument("--no-gang-smoke", action="store_true",
                   help="skip the 2-process gang serving smoke")
    p.add_argument("--no-store-smoke", action="store_true",
                   help="skip the content-store publish/dedup/gc smoke")
    args = p.parse_args(argv)

    cmd = [sys.executable, "-m", "distributed_machine_learning_tpu",
           "lint", "--format=sarif"]
    if not args.no_jax:
        cmd.append("--jax")
    if not args.full:
        cmd.append(f"--changed={args.ref or _default_ref()}")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # the gate must not touch TPUs
    proc = subprocess.run(
        cmd, cwd=REPO, capture_output=True, text=True, env=env,
    )
    if proc.stderr:
        sys.stderr.write(proc.stderr)
    out = proc.stdout.strip()
    if not out:
        return proc.returncode
    try:
        sarif = json.loads(out)
    except json.JSONDecodeError:
        # --changed with no .py files changed prints a plain line, not
        # SARIF; surface it and pass the exit code through.
        print(out)
        return proc.returncode
    with open(args.out, "w") as f:
        json.dump(sarif, f, indent=2)
        f.write("\n")
    results = sarif["runs"][0]["results"]
    live = [r for r in results if not r.get("suppressions")]
    for r in live:
        loc = r["locations"][0]["physicalLocation"]
        print(f"{loc['artifactLocation']['uri']}:"
              f"{loc['region']['startLine']}: {r['ruleId']} "
              f"{r['message']['text'].splitlines()[0]}")
    print(f"lint gate: {len(live)} live finding(s), "
          f"{len(results) - len(live)} suppressed/baselined "
          f"-> {args.out}")
    if proc.returncode == 0 and not args.no_perf_guard:
        rc = _obs_perf_guard(env)
        if rc:
            return rc
    if proc.returncode == 0 and not args.no_quant_smoke:
        rc = _quant_smoke(env)
        if rc:
            return rc
    if proc.returncode == 0 and not args.no_loop_smoke:
        rc = _loop_smoke(env)
        if rc:
            return rc
    if proc.returncode == 0 and not args.no_head_smoke:
        rc = _head_crash_smoke(env)
        if rc:
            return rc
    if proc.returncode == 0 and not args.no_gang_smoke:
        rc = _gang_serve_smoke(env)
        if rc:
            return rc
    if proc.returncode == 0 and not args.no_store_smoke:
        rc = _store_smoke(env)
        if rc:
            return rc
    return proc.returncode


# Generous CI bounds (shared-runner jitter); the tier-1 guard in
# tests/test_obs_plane.py measures the same function.
PERF_GUARD_NS_BUDGET = 1500.0
PERF_GUARD_BLOCK_BUDGET = 16


def _obs_perf_guard(env) -> int:
    """Run obs.disabled_path_overhead in a child (DML_OBS_PERF_GUARD=1)
    and fail the gate if the disabled span path regressed."""
    env = dict(env, DML_OBS_PERF_GUARD="1")
    code = (
        "import json\n"
        "from distributed_machine_learning_tpu import obs\n"
        "print(json.dumps(min((obs.disabled_path_overhead()\n"
        "      for _ in range(3)), key=lambda r: r['ns_per_span'])))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, capture_output=True, text=True, env=env, timeout=300,
    )
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        print("obs perf guard: FAILED to run")
        return 1
    measured = json.loads(proc.stdout.strip().splitlines()[-1])
    ok = (
        measured["ns_per_span"] <= PERF_GUARD_NS_BUDGET
        and measured["net_blocks"] <= PERF_GUARD_BLOCK_BUDGET
    )
    print(
        f"obs perf guard: {measured['ns_per_span']:.0f} ns/span, "
        f"{measured['net_blocks']} net blocks over {measured['iters']} "
        f"disabled spans (budget {PERF_GUARD_NS_BUDGET:.0f} ns / "
        f"{PERF_GUARD_BLOCK_BUDGET} blocks) -> "
        f"{'ok' if ok else 'REGRESSED'}"
    )
    return 0 if ok else 1


def _quant_smoke(env) -> int:
    """Quantize-export-load roundtrip in a child (JAX_PLATFORMS=cpu): a
    tiny mlp quantizes to int8, writes a bundle, loads it back, and the
    served predictions stay within the calibrated delta — the quant/
    manifest contract, gated like a lint finding."""
    code = (
        "import json, tempfile\n"
        "import jax, numpy as np\n"
        "from distributed_machine_learning_tpu import quant, serve\n"
        "from distributed_machine_learning_tpu.models import build_model\n"
        "from distributed_machine_learning_tpu.serve import export as ex\n"
        "config = {'model': 'mlp', 'hidden_sizes': [8]}\n"
        "model = build_model(config)\n"
        "x = np.random.default_rng(0).normal(\n"
        "    size=(8, 6, 4)).astype(np.float32)\n"
        "variables = model.init(jax.random.PRNGKey(0), x,\n"
        "                       deterministic=True)\n"
        "block = quant.build_quant_block(model, variables, 'int8', x)\n"
        "qvars = block.pop('_variables')\n"
        "out = tempfile.mkdtemp(prefix='quant_smoke_')\n"
        "ex.write_bundle(out, {'bundle_version': ex.BUNDLE_VERSION,\n"
        "                      'config': config, 'precision': 'int8',\n"
        "                      'quant': block}, qvars)\n"
        "bundle = serve.load_bundle(out)\n"
        "assert bundle.precision == 'int8'\n"
        "eng = serve.InferenceEngine(bundle, max_bucket=8,\n"
        "                            persistent_cache=False)\n"
        "q = eng.predict(x)\n"
        "f = np.asarray(model.apply(variables, x, deterministic=True))\n"
        "mape = float(np.mean(np.abs(q - f) / (np.abs(f) + 1e-8)))\n"
        "delta = bundle.quality_delta_mape\n"
        "assert mape <= delta * 1.5 + 1e-3, (mape, delta)\n"
        "print(json.dumps({'quality_delta_mape': round(delta, 6),\n"
        "                  'served_mape': round(mape, 6),\n"
        "                  'compression': block.get('compression')}))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, capture_output=True, text=True, env=env, timeout=300,
    )
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        print("quant smoke: FAILED")
        return 1
    print(f"quant smoke: ok {proc.stdout.strip().splitlines()[-1]}")
    return 0


def _loop_smoke(env) -> int:
    """One self-healing episode in a child (JAX_PLATFORMS=cpu): a tiny
    served mlp drifts, the monitor triggers, and the controller's
    journaled retrain-gate-swap-probation episode must land PROMOTED
    with zero serving-path compiles — the loop/ contract, gated like a
    lint finding."""
    code = (
        "import json, os, tempfile\n"
        "import numpy as np\n"
        "from distributed_machine_learning_tpu import chaos, loop, serve\n"
        "from distributed_machine_learning_tpu.models import build_model\n"
        "from distributed_machine_learning_tpu.serve import export as ex\n"
        "from distributed_machine_learning_tpu.tune._regression_program \\\n"
        "    import detect_call_convention\n"
        "W = np.array([0.7, -0.4, 1.1], np.float32)\n"
        "DRIFT = {'at_request': 0, 'feature_shift': 2.5,\n"
        "         'label_shift': 0.5, 'seed': 11}\n"
        "def make_xy(n, seed, drifted=False):\n"
        "    r = np.random.default_rng(seed)\n"
        "    x = r.standard_normal((n, 4, 3)).astype(np.float32)\n"
        "    y = (x[:, -2:, :] @ W).mean(axis=1, keepdims=True)\n"
        "    if drifted:\n"
        "        x, y = chaos.apply_drift(DRIFT, x, y)\n"
        "    return x.astype(np.float32), y.astype(np.float32)\n"
        "def data_fn(kind):\n"
        "    seeds = {'train': 100, 'holdout': 200, 'probation': 300}\n"
        "    return make_xy(48, seeds[kind], drifted=True)\n"
        "config = {'model': 'mlp', 'hidden_sizes': [8], 'seed': 3}\n"
        "x, y = make_xy(64, 1)\n"
        "probe, _ = detect_call_convention(build_model(config), x[:1])\n"
        "variables, _ = loop.fine_tune(config, {'params': probe['params']},\n"
        "                              x, y, epochs=4, learning_rate=0.05,\n"
        "                              seed=0)\n"
        "root = tempfile.mkdtemp(prefix='loop_smoke_')\n"
        "inc = os.path.join(root, 'incumbent')\n"
        "ex.write_bundle(inc, {'bundle_version': ex.BUNDLE_VERSION,\n"
        "                      'config': config, 'precision': 'f32'},\n"
        "                variables)\n"
        "srv = serve.PredictionServer(serve.load_bundle(inc), port=0,\n"
        "                             num_replicas=1, max_bucket=16)\n"
        "srv.warmup(x[:1])\n"
        "drift = loop.DriftMonitor(window=16, z_threshold=4.0, sustain=3)\n"
        "srv.metrics.attach_drift(drift)\n"
        "for i in range(40):\n"
        "    xb, _ = make_xy(4, 1000 + i, drifted=i >= 18)\n"
        "    preds = np.asarray(srv.replicas.predict(xb))\n"
        "    srv.metrics.observe_streams(float(np.mean(xb)),\n"
        "                                float(np.mean(preds)))\n"
        "ctl = loop.SelfHealingController(\n"
        "    srv, loop.LoopJournal(os.path.join(root, 'loop.json')),\n"
        "    drift, data_fn, root,\n"
        "    loop.LoopConfig(retrain_epochs=3, probation_batches=2))\n"
        "outcome = ctl.poll()\n"
        "assert outcome is not None, 'drift never triggered'\n"
        "assert outcome['state'] == 'promoted', outcome\n"
        "stats = srv.replicas.program_stats()\n"
        "assert stats['new_programs_since_warmup'] == 0, stats\n"
        "srv.close()\n"
        "print(json.dumps({'state': outcome['state'],\n"
        "                  'probation_mape':\n"
        "                      round(outcome['probation_mape'], 4),\n"
        "                  'incumbent_mape':\n"
        "                      round(outcome['incumbent_mape'], 4)}))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, capture_output=True, text=True, env=env, timeout=300,
    )
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        print("loop smoke: FAILED")
        return 1
    print(f"loop smoke: ok {proc.stdout.strip().splitlines()[-1]}")
    return 0


def _head_crash_smoke(env) -> int:
    """Durable-control-plane smoke in a child (JAX_PLATFORMS=cpu): a tiny
    sweep's driver is killed (``os._exit(86)`` mid-journal-append, the
    chaos ``kill_head_at`` fault) at decision 4, ``resume="auto"``
    replays the write-ahead journal, and the finished experiment must
    name the SAME best trial as an uninterrupted control — the tune
    journal contract, gated like a lint finding."""
    code = (
        "import json, tempfile\n"
        "from distributed_machine_learning_tpu.tune import crashsim\n"
        "root = tempfile.mkdtemp(prefix='head_crash_smoke_')\n"
        "spec = dict(num_samples=3, epochs=3, seed=5)\n"
        "ctrl = crashsim.control_run(root, 'ctrl', **spec)\n"
        "out = crashsim.killed_then_resumed(root, 'crash', kill_at=4,\n"
        "                                   **spec)\n"
        "assert out['crash_rc'] == crashsim.HEAD_KILL_EXIT\n"
        "res = out['result']\n"
        "assert res['best_trial'] == ctrl['best_trial'], (res, ctrl)\n"
        "assert res['best_score'] == ctrl['best_score'], (res, ctrl)\n"
        "assert out['journal']['committed'] is True\n"
        "assert out['journal']['head_starts'] == 2\n"
        "print(json.dumps({'best_trial': res['best_trial'],\n"
        "                  'detect_s': out['detect_s'],\n"
        "                  'replay_s': out['replay_s'],\n"
        "                  'requeue_s': out['requeue_s']}))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, capture_output=True, text=True, env=env, timeout=300,
    )
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        print("head-crash smoke: FAILED")
        return 1
    print(f"head-crash smoke: ok {proc.stdout.strip().splitlines()[-1]}")
    return 0


def _gang_serve_smoke(env) -> int:
    """Pod-scale serving smoke in a child (JAX_PLATFORMS=cpu): a 2-process
    serving GANG loads a TP-sharded bundle, reshards it onto the spanning
    mesh, and must answer bit-identically to the single-process engine
    with ZERO serving-path compiles after warmup — the serve/gang
    contract, gated like a lint finding.  Containers that cannot run
    2-process jax.distributed over CPU collectives skip (rc 0) WITH the
    probe's evidence, same as the tier-1 gang tests."""
    sys.path.insert(0, os.path.join(REPO, "tests"))
    try:
        import _env_probe
        ok, why = _env_probe.multiprocess_cpu_collectives()
    finally:
        sys.path.remove(os.path.join(REPO, "tests"))
    if not ok:
        print(f"gang smoke: skipped (2-process jax.distributed "
              f"unavailable here: {why})")
        return 0
    # Dense_0 column-sharded into a WIDER Dense_1: propagation all-gathers
    # the narrow activations (exact) instead of psumming wide partials, so
    # the gang must match the single-process engine bit for bit.
    code = (
        "import json, tempfile\n"
        "import jax, numpy as np\n"
        "from distributed_machine_learning_tpu import serve\n"
        "from distributed_machine_learning_tpu.models import build_model\n"
        "from distributed_machine_learning_tpu.serve import export as ex\n"
        "from distributed_machine_learning_tpu.serve.gang import "
        "GangReplica\n"
        "config = {'model': 'mlp', 'hidden_sizes': [16, 64],\n"
        "          'partition_rules': [\n"
        "              ['params/Dense_0/kernel', [None, 'tp']],\n"
        "              ['params/Dense_0/bias', ['tp']],\n"
        "              ['.*', []]]}\n"
        "model = build_model(config)\n"
        "x = np.random.default_rng(0).normal(\n"
        "    size=(5, 6, 4)).astype(np.float32)\n"
        "variables = model.init(jax.random.PRNGKey(0), x,\n"
        "                       deterministic=True)\n"
        "out = tempfile.mkdtemp(prefix='gang_smoke_')\n"
        "ex.write_bundle(out, {'bundle_version': ex.BUNDLE_VERSION,\n"
        "                      'config': config, 'precision': 'f32'},\n"
        "                variables)\n"
        "bundle = serve.load_bundle(out)\n"
        "ref = serve.InferenceEngine(bundle, max_bucket=8,\n"
        "                            persistent_cache=False).predict(x)\n"
        "gang = GangReplica(0, bundle, processes=2, max_bucket=8)\n"
        "try:\n"
        "    warm = gang.warmup(x)\n"
        "    assert warm['topology']['process_count'] == 2, warm\n"
        "    got = gang.submit(x).result(timeout=120)\n"
        "    assert np.array_equal(got, ref), 'gang != single-process'\n"
        "    stats = gang.engine.program_stats()\n"
        "    assert stats['programs'] == warm['programs'], (\n"
        "        'serving-path compile after warmup', stats)\n"
        "finally:\n"
        "    gang.retire()\n"
        "print(json.dumps({'processes': 2,\n"
        "                  'programs': warm['programs'],\n"
        "                  'bit_identical': True}))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, capture_output=True, text=True, env=env, timeout=480,
    )
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        print("gang smoke: FAILED")
        return 1
    print(f"gang smoke: ok {proc.stdout.strip().splitlines()[-1]}")
    return 0


def _store_smoke(env) -> int:
    """Content-store smoke in a child (JAX_PLATFORMS=cpu): two checkpoint
    generations that share a leaf publish through the store (the second
    save must be a dedup hit, not a second copy), load back bit-identical,
    GC after deleting generation 1 reclaims only its unique blobs, and
    verify re-hashes clean — the store/ contract, gated like a lint
    finding."""
    code = (
        "import json, os, tempfile\n"
        "import numpy as np\n"
        "from distributed_machine_learning_tpu import store\n"
        "from distributed_machine_learning_tpu.ckpt import format as fmt\n"
        "root = tempfile.mkdtemp(prefix='store_smoke_')\n"
        "tree1 = {'w': np.arange(4096, dtype=np.float32),\n"
        "         'b': np.ones(512, np.float32)}\n"
        "tree2 = {'w': tree1['w'],  # unchanged -> dedup hit\n"
        "         'b': np.full(512, 2.0, np.float32)}\n"
        "g1 = os.path.join(root, 'gen_000001')\n"
        "g2 = os.path.join(root, 'gen_000002')\n"
        "before = store.get_metrics().snapshot()\n"
        "fmt.save_sharded(g1, tree1)\n"
        "fmt.save_sharded(g2, tree2)\n"
        "d = store.get_metrics().delta_since(before)\n"
        "assert d['dedup_hits'] > 0, d\n"
        "assert d['bytes_physical'] < d['bytes_logical'], d\n"
        "got = fmt.load_sharded(g2)\n"
        "assert np.array_equal(np.asarray(got['w']), tree2['w'])\n"
        "assert np.array_equal(np.asarray(got['b']), tree2['b'])\n"
        "cas = store.get_store(store.store_root_for(g1))\n"
        "fmt.delete_generation(g1)\n"
        "swept = cas.gc()\n"
        "assert swept['collected'] > 0 and swept['retained'] > 0, swept\n"
        "got = fmt.load_sharded(g2)  # survivor still loads post-GC\n"
        "assert np.array_equal(np.asarray(got['b']), tree2['b'])\n"
        "checked = cas.verify()\n"
        "assert not checked['corrupt'], checked\n"
        "print(json.dumps({'dedup_hits': d['dedup_hits'],\n"
        "                  'bytes_logical': d['bytes_logical'],\n"
        "                  'bytes_physical': d['bytes_physical'],\n"
        "                  'gc_collected': swept['collected'],\n"
        "                  'verified_blobs': checked['blobs']}))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO, capture_output=True, text=True, env=env, timeout=300,
    )
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr)
        print("store smoke: FAILED")
        return 1
    print(f"store smoke: ok {proc.stdout.strip().splitlines()[-1]}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
